"""Cluster test harnesses.

Reference: python/ray/cluster_utils.py:135. Two levels of realism:

- ``Cluster``: N *logical* nodes in one GCS (the reference's in-process
  harness) — multi-node scheduling/failover tests in one process tree,
  all sharing the head's object store.
- ``DaemonCluster``: head GCS listening on TCP plus N real node-daemon
  *processes* (ray_tpu._private.raylet), each with its own shm pool and
  object-transfer server — the full multi-host control + data plane on
  one machine, the way the reference's fake_multi_node provider runs
  real raylets locally.
"""
from __future__ import annotations

import os
import secrets
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

import ray_tpu
from ._private.worker import global_client


class ClusterNode:
    def __init__(self, node_id: bytes, resources: Dict[str, float]):
        self.node_id = node_id
        self.resources = resources

    def __repr__(self):
        return f"ClusterNode({self.node_id.hex()[:8]}, {self.resources})"


class Cluster:
    def __init__(
        self,
        initialize_head: bool = True,
        head_node_args: Optional[dict] = None,
    ):
        self._nodes = []
        if initialize_head:
            ray_tpu.init(**(head_node_args or {"num_cpus": 1}),
                         ignore_reinit_error=True)

    def add_node(self, *, num_cpus: float = 1, num_tpus: float = 0,
                 resources: Optional[Dict[str, float]] = None,
                 label: str = "") -> ClusterNode:
        res = {"CPU": float(num_cpus)}
        if num_tpus:
            res["TPU"] = float(num_tpus)
        res.update(resources or {})
        reply = global_client().request(
            {"type": "add_node", "resources": res, "label": label}
        )
        if not reply.get("ok"):
            raise RuntimeError(f"add_node failed: {reply}")
        node = ClusterNode(reply["node_id"], res)
        self._nodes.append(node)
        return node

    def remove_node(self, node: ClusterNode) -> None:
        global_client().request(
            {"type": "remove_node", "node_id": node.node_id}
        )
        if node in self._nodes:
            self._nodes.remove(node)

    def shutdown(self):
        ray_tpu.shutdown()


def _pinned_pythonpath() -> str:
    """PYTHONPATH with this very package's root first: subprocesses
    (head_main, raylet) must resolve ray_tpu even when the launching
    process runs from an unrelated cwd."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(
        ray_tpu.__file__
    )))
    return os.pathsep.join(
        p for p in (repo, os.environ.get("PYTHONPATH", "")) if p
    )


class SupervisedHead:
    """Standalone head process (``ray_tpu._private.head_main``) under a
    tiny supervisor: when the head dies — SIGKILL'd by a chaos test or
    by a ``kill:gcs.*`` kill point inside it — it is relaunched on the
    SAME port and session dir, so the new head restores the persisted
    GCS tables and live drivers/raylets/workers reconnect to it
    (reference: the external supervisor keeping gcs_server alive that
    NotifyGCSRestart assumes).

    The head-failover chaos scenario drives this; tests use it to kill
    a live head out from under a connected driver.
    """

    def __init__(
        self,
        session_dir: str,
        port: Optional[int] = None,
        authkey: Optional[bytes] = None,
        num_cpus: float = 0.0,
        env: Optional[Dict[str, str]] = None,
    ):
        os.makedirs(session_dir, exist_ok=True)
        self.session_dir = session_dir
        if port is None:
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
            s.close()
        self.port = int(port)
        self.authkey = authkey or secrets.token_bytes(16)
        self.num_cpus = num_cpus
        self._env = dict(env or {})
        self._lock = threading.Lock()
        self._stopping = False
        self._gen = 0
        self._env.setdefault("PYTHONPATH", _pinned_pythonpath())
        #: Completed restart count (a kill that came back).
        self.restarts = 0
        self.proc: Optional[subprocess.Popen] = None
        self._start_head()
        self._watcher = threading.Thread(
            target=self._watch, name="head-supervisor", daemon=True
        )
        self._watcher.start()

    @property
    def tcp_address(self) -> str:
        return f"127.0.0.1:{self.port}"

    @property
    def address(self) -> str:
        """``ray_tpu.init(address=...)`` form (host:port?authkey)."""
        return f"{self.tcp_address}?{self.authkey.hex()}"

    def _start_head(self) -> None:
        self._gen += 1
        log_path = os.path.join(self.session_dir, f"head-{self._gen}.err")
        with open(log_path, "wb") as log:
            proc = subprocess.Popen(
                [
                    sys.executable, "-m", "ray_tpu._private.head_main",
                    "--session-dir", self.session_dir,
                    "--tcp-port", str(self.port),
                    "--authkey", self.authkey.hex(),
                    "--num-cpus", str(self.num_cpus),
                ],
                env={**os.environ, **self._env},
                stdout=subprocess.DEVNULL,
                stderr=log,
            )
        deadline = time.time() + 30
        while time.time() < deadline:
            if proc.poll() is not None:
                with open(log_path, "rb") as f:
                    tail = f.read()[-2000:].decode(errors="replace")
                raise RuntimeError(f"head exited during startup: {tail}")
            try:
                with open(log_path, "rb") as f:
                    if b"head up" in f.read():
                        break
            except OSError:
                pass
            time.sleep(0.05)
        else:
            proc.kill()
            raise TimeoutError("head did not come up within 30s")
        self.proc = proc

    def _watch(self) -> None:
        while True:
            proc = self.proc
            if proc is None:
                return
            proc.wait()
            with self._lock:
                if self._stopping:
                    return
            # Relaunch on the same address/session: persisted tables
            # restore; everyone reconnects. A port still draining from
            # the old process retries briefly — on the one retry
            # policy (chaos.Backoff: jittered, capped), not a fixed
            # sleep (raylint fixed-sleep-retry).
            from ._private.chaos import Backoff

            bo = Backoff(base_s=0.5, cap_s=4.0)
            for attempt in range(5):
                try:
                    self._start_head()
                    break
                except (RuntimeError, TimeoutError, OSError):
                    if attempt == 4:
                        return  # supervisor gives up: head stays dead
                    bo.sleep()
            with self._lock:
                if self._stopping:
                    return
                self.restarts += 1

    def kill(self) -> None:
        """SIGKILL the current head (the supervisor restarts it)."""
        proc = self.proc
        if proc is not None:
            try:
                proc.kill()
            except OSError:
                pass

    def wait_restarted(self, n: int, timeout: float = 60.0) -> bool:
        """Block until at least ``n`` restarts completed."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            with self._lock:
                if self.restarts >= n:
                    return True
            time.sleep(0.1)
        return False

    def stop(self) -> None:
        with self._lock:
            self._stopping = True
        proc = self.proc
        if proc is not None:
            proc.terminate()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()


class DaemonCluster:
    """Head + real node-daemon subprocesses over the TCP control plane."""

    def __init__(self, head_node_args: Optional[dict] = None):
        args = dict(head_node_args or {"num_cpus": 1})
        args.setdefault("tcp_port", 0)
        ray_tpu.init(**args, ignore_reinit_error=True)
        from ._private.worker import _global

        if _global.node is None or not _global.node.tcp_address:
            raise RuntimeError(
                "DaemonCluster needs a fresh TCP-enabled head; an existing "
                "session without tcp_port is already initialized — "
                "shutdown() first"
            )
        self.head_address = _global.node.tcp_address
        self.authkey = _global.node.authkey
        self._daemons: List[subprocess.Popen] = []

    @classmethod
    def attach(
        cls,
        head_address: Optional[str] = None,
        authkey: Optional[bytes] = None,
    ) -> "DaemonCluster":
        """Attach to the ALREADY-initialized TCP-enabled head instead of
        starting one (``__init__`` refuses a live session). Daemons
        added through the attached handle are owned by it — callers
        shut them down via ``kill_node``, not ``shutdown`` (the session
        belongs to whoever initialized it).

        Pass ``head_address``/``authkey`` explicitly to attach to an
        EXTERNAL head (e.g. a ``SupervisedHead``) this process joined
        via ``init(address=...)`` — there is no in-process node then."""
        if head_address is not None and authkey is not None:
            self = cls.__new__(cls)
            self.head_address = head_address
            self.authkey = authkey
            self._daemons = []
            return self
        from ._private.worker import _global

        if _global.node is None or not _global.node.tcp_address:
            raise RuntimeError(
                "DaemonCluster.attach needs an initialized TCP-enabled "
                "head (init(tcp_port=...))"
            )
        self = cls.__new__(cls)
        self.head_address = _global.node.tcp_address
        self.authkey = _global.node.authkey
        self._daemons = []
        return self

    def add_node(
        self,
        *,
        num_cpus: float = 1,
        num_tpus: float = 0,
        resources: Optional[Dict[str, float]] = None,
        label: str = "",
        wait: bool = True,
        env: Optional[Dict[str, str]] = None,
    ) -> subprocess.Popen:
        """``env`` overlays the daemon's environment — chaos tests use
        it to install a per-node fault schedule (e.g. a partition spec
        that only the victim raylet and its workers enforce)."""
        import json

        res = {"CPU": float(num_cpus)}
        if num_tpus:
            res["TPU"] = float(num_tpus)
        res.update(resources or {})
        before = len(ray_tpu.nodes())
        env = {
            **os.environ,
            "PYTHONPATH": _pinned_pythonpath(),
            **(env or {}),
        }
        proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "ray_tpu._private.raylet",
                "--address",
                self.head_address,
                "--authkey",
                self.authkey.hex(),
                "--resources",
                json.dumps(res),
                "--label",
                label,
                "--transfer-host",
                "127.0.0.1",
            ],
            env=env,
            stderr=subprocess.PIPE,
            stdout=subprocess.PIPE,
        )
        self._daemons.append(proc)
        if wait:
            deadline = time.time() + 30
            while time.time() < deadline:
                if len(ray_tpu.nodes()) > before:
                    return proc
                if proc.poll() is not None:
                    _, err = proc.communicate()
                    raise RuntimeError(
                        f"node daemon exited: {err.decode(errors='replace')}"
                    )
                time.sleep(0.05)
            raise TimeoutError("node daemon did not register within 30s")
        return proc

    def kill_node(self, proc: subprocess.Popen, graceful: bool = False):
        proc.terminate() if graceful else proc.kill()
        proc.wait(timeout=10)
        if proc in self._daemons:
            self._daemons.remove(proc)

    def shutdown(self):
        ray_tpu.shutdown()
        deadline = time.time() + 5
        for proc in self._daemons:
            try:
                proc.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
        self._daemons.clear()
