"""Cluster test harnesses.

Reference: python/ray/cluster_utils.py:135. Two levels of realism:

- ``Cluster``: N *logical* nodes in one GCS (the reference's in-process
  harness) — multi-node scheduling/failover tests in one process tree,
  all sharing the head's object store.
- ``DaemonCluster``: head GCS listening on TCP plus N real node-daemon
  *processes* (ray_tpu._private.raylet), each with its own shm pool and
  object-transfer server — the full multi-host control + data plane on
  one machine, the way the reference's fake_multi_node provider runs
  real raylets locally.
"""
from __future__ import annotations

import subprocess
import sys
import time
from typing import Dict, List, Optional

import ray_tpu
from ._private.worker import global_client


class ClusterNode:
    def __init__(self, node_id: bytes, resources: Dict[str, float]):
        self.node_id = node_id
        self.resources = resources

    def __repr__(self):
        return f"ClusterNode({self.node_id.hex()[:8]}, {self.resources})"


class Cluster:
    def __init__(
        self,
        initialize_head: bool = True,
        head_node_args: Optional[dict] = None,
    ):
        self._nodes = []
        if initialize_head:
            ray_tpu.init(**(head_node_args or {"num_cpus": 1}),
                         ignore_reinit_error=True)

    def add_node(self, *, num_cpus: float = 1, num_tpus: float = 0,
                 resources: Optional[Dict[str, float]] = None,
                 label: str = "") -> ClusterNode:
        res = {"CPU": float(num_cpus)}
        if num_tpus:
            res["TPU"] = float(num_tpus)
        res.update(resources or {})
        reply = global_client().request(
            {"type": "add_node", "resources": res, "label": label}
        )
        if not reply.get("ok"):
            raise RuntimeError(f"add_node failed: {reply}")
        node = ClusterNode(reply["node_id"], res)
        self._nodes.append(node)
        return node

    def remove_node(self, node: ClusterNode) -> None:
        global_client().request(
            {"type": "remove_node", "node_id": node.node_id}
        )
        if node in self._nodes:
            self._nodes.remove(node)

    def shutdown(self):
        ray_tpu.shutdown()


class DaemonCluster:
    """Head + real node-daemon subprocesses over the TCP control plane."""

    def __init__(self, head_node_args: Optional[dict] = None):
        args = dict(head_node_args or {"num_cpus": 1})
        args.setdefault("tcp_port", 0)
        ray_tpu.init(**args, ignore_reinit_error=True)
        from ._private.worker import _global

        if _global.node is None or not _global.node.tcp_address:
            raise RuntimeError(
                "DaemonCluster needs a fresh TCP-enabled head; an existing "
                "session without tcp_port is already initialized — "
                "shutdown() first"
            )
        self.head_address = _global.node.tcp_address
        self.authkey = _global.node.authkey
        self._daemons: List[subprocess.Popen] = []

    @classmethod
    def attach(cls) -> "DaemonCluster":
        """Attach to the ALREADY-initialized TCP-enabled head instead of
        starting one (``__init__`` refuses a live session). Daemons
        added through the attached handle are owned by it — callers
        shut them down via ``kill_node``, not ``shutdown`` (the session
        belongs to whoever initialized it)."""
        from ._private.worker import _global

        if _global.node is None or not _global.node.tcp_address:
            raise RuntimeError(
                "DaemonCluster.attach needs an initialized TCP-enabled "
                "head (init(tcp_port=...))"
            )
        self = cls.__new__(cls)
        self.head_address = _global.node.tcp_address
        self.authkey = _global.node.authkey
        self._daemons = []
        return self

    def add_node(
        self,
        *,
        num_cpus: float = 1,
        num_tpus: float = 0,
        resources: Optional[Dict[str, float]] = None,
        label: str = "",
        wait: bool = True,
    ) -> subprocess.Popen:
        import json

        res = {"CPU": float(num_cpus)}
        if num_tpus:
            res["TPU"] = float(num_tpus)
        res.update(resources or {})
        before = len(ray_tpu.nodes())
        proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "ray_tpu._private.raylet",
                "--address",
                self.head_address,
                "--authkey",
                self.authkey.hex(),
                "--resources",
                json.dumps(res),
                "--label",
                label,
                "--transfer-host",
                "127.0.0.1",
            ],
            stderr=subprocess.PIPE,
            stdout=subprocess.PIPE,
        )
        self._daemons.append(proc)
        if wait:
            deadline = time.time() + 30
            while time.time() < deadline:
                if len(ray_tpu.nodes()) > before:
                    return proc
                if proc.poll() is not None:
                    _, err = proc.communicate()
                    raise RuntimeError(
                        f"node daemon exited: {err.decode(errors='replace')}"
                    )
                time.sleep(0.05)
            raise TimeoutError("node daemon did not register within 30s")
        return proc

    def kill_node(self, proc: subprocess.Popen, graceful: bool = False):
        proc.terminate() if graceful else proc.kill()
        proc.wait(timeout=10)
        if proc in self._daemons:
            self._daemons.remove(proc)

    def shutdown(self):
        ray_tpu.shutdown()
        deadline = time.time() + 5
        for proc in self._daemons:
            try:
                proc.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
        self._daemons.clear()
