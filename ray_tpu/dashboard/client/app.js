/* ray_tpu dashboard SPA.
 *
 * Hash-routed single-page app over the JSON API served by
 * ray_tpu/dashboard/__init__.py (reference: dashboard/client/src — a
 * React app over the head's REST API; this is the no-build-step
 * equivalent: plain ES modules-free JS, zero dependencies).
 *
 * Pages: overview (resource cards + sparklines), nodes/workers/actors/
 * tasks/placement_groups tables, per-task and per-actor drill-down,
 * jobs, serve apps, log tail, and a flamegraph viewer over the folded
 * stacks the sampling profiler returns.
 */
"use strict";

const $ = (sel) => document.querySelector(sel);

function esc(s) {
  return String(s).replace(/[&<>"']/g, (c) => ({
    "&": "&amp;", "<": "&lt;", ">": "&gt;", '"': "&quot;", "'": "&#39;",
  })[c]);
}

async function getJSON(url) {
  const r = await fetch(url);
  if (!r.ok) throw new Error(`${url}: HTTP ${r.status} ${await r.text()}`);
  return r.json();
}

// ------------------------------------------------------------- router

const PAGES = {};
let refreshTimer = null;

function route() {
  const hash = location.hash.replace(/^#\/?/, "") || "overview";
  const [page, ...rest] = hash.split("/");
  const fn = PAGES[page] || PAGES.overview;
  document.querySelectorAll("nav a").forEach((a) => {
    a.classList.toggle("active", a.dataset.page === page);
  });
  if (refreshTimer) { clearInterval(refreshTimer); refreshTimer = null; }
  const render = async (force) => {
    // Don't yank a form control out from under the user: the refresh
    // replaces #page wholesale, which would wipe in-progress typing.
    const el = document.activeElement;
    if (!force && el && $("#page").contains(el) &&
        ["INPUT", "SELECT", "TEXTAREA"].includes(el.tagName)) return;
    try {
      await fn(rest.join("/"));
    } catch (e) {
      $("#page").innerHTML =
        `<div class="err-banner">${esc(e.message || e)}</div>`;
    }
  };
  render(true);
  // Live refresh for everything except the (expensive) profiler page.
  if (page !== "profile") refreshTimer = setInterval(render, 3000);
}
window.addEventListener("hashchange", route);
window.addEventListener("load", route);

// ------------------------------------------------------- shared pieces

function statusClass(s) {
  s = String(s).toUpperCase();
  if (["ALIVE", "RUNNING", "SUCCEEDED", "FINISHED", "TERMINATED", "HEALTHY",
       "DEPLOYED"].includes(s)) return "ok";
  if (["PENDING", "RESTARTING", "DEPLOYING", "STOPPED", "NOT_STARTED",
       "UPDATING"].includes(s)) return "warn";
  if (["DEAD", "ERROR", "FAILED", "UNHEALTHY", "DEPLOY_FAILED"].includes(s))
    return "err";
  return "";
}

function cellHTML(kind, col, val) {
  if (val === null || val === undefined) return "";
  if (col === "status" || col === "state")
    return `<span class="status ${statusClass(val)}">${esc(val)}</span>`;
  if (kind === "tasks" && col === "task_id")
    return `<a href="#/task/${encodeURIComponent(val)}">${esc(val)}</a>`;
  if ((kind === "actors" || kind === "tasks") && col === "actor_id" && val)
    return `<a href="#/actor/${encodeURIComponent(val)}">${esc(val)}</a>`;
  if (kind === "workers" && col === "worker_id")
    return `<a href="#/profile/${encodeURIComponent(val)}">${esc(val)}</a>`;
  if (typeof val === "object") return esc(JSON.stringify(val));
  return esc(val);
}

function renderTable(kind, items, filter) {
  if (filter) {
    const f = filter.toLowerCase();
    items = items.filter((it) =>
      JSON.stringify(it).toLowerCase().includes(f));
  }
  if (!items.length) return '<p class="muted">(none)</p>';
  const cols = Object.keys(items[0]);
  let html = "<table><thead><tr>" +
    cols.map((c) => `<th>${esc(c)}</th>`).join("") + "</tr></thead><tbody>";
  for (const it of items.slice(0, 200)) {
    html += "<tr>" + cols.map(
      (c) => `<td>${cellHTML(kind, c, it[c])}</td>`).join("") + "</tr>";
  }
  return html + "</tbody></table>" + (items.length > 200
    ? `<p class="muted">showing 200 of ${items.length}</p>` : "");
}

const tableFilters = {};  // page -> current filter text, survives refresh

function tablePage(kind, title) {
  return async () => {
    const items = await getJSON(`/api/${kind}`);
    const f = tableFilters[kind] || "";
    $("#page").innerHTML =
      `<h1>${esc(title)} <span class="muted">(${items.length})</span></h1>` +
      `<div class="toolbar"><input id="filter" placeholder="filter…" ` +
      `value="${esc(f)}"></div><div id="tbl">` +
      renderTable(kind, items, f) + "</div>";
    $("#filter").addEventListener("input", (e) => {
      tableFilters[kind] = e.target.value;
      $("#tbl").innerHTML = renderTable(kind, items, e.target.value);
    });
  };
}

function spark(points, label, w = 180, h = 40) {
  if (!points.length) return "";
  const max = Math.max(...points, 1e-9), min = Math.min(...points, 0);
  const xs = points.map((p, i) => [
    (i * w) / Math.max(points.length - 1, 1),
    h - 2 - ((p - min) / Math.max(max - min, 1e-9)) * (h - 4)]);
  const path = xs.map(([x, y], i) =>
    (i ? "L" : "M") + x.toFixed(1) + " " + y.toFixed(1)).join(" ");
  return `<figure><svg class="spark" width="${w}" height="${h}">` +
    `<path d="${path}" fill="none" stroke="#2458c5" stroke-width="1.5"/>` +
    `</svg><figcaption>${esc(label)} ` +
    `(now: ${points[points.length - 1].toFixed(1)})</figcaption></figure>`;
}

// --------------------------------------------------------------- pages

PAGES.overview = async () => {
  const [cluster, ts, nodes, actors] = await Promise.all([
    getJSON("/api/cluster"), getJSON("/api/metrics_timeseries"),
    getJSON("/api/nodes"), getJSON("/api/actors"),
  ]);
  const aliveNodes = nodes.filter((n) => n.alive).length;
  const aliveActors = actors.filter(
    (a) => String(a.state).toUpperCase() === "ALIVE").length;
  let html = "<h1>Cluster overview</h1><div class='cards'>";
  html += `<div class="card"><div class="num">${aliveNodes}</div>` +
    `<div class="label">nodes alive</div></div>`;
  html += `<div class="card"><div class="num">${aliveActors}</div>` +
    `<div class="label">actors alive</div></div>`;
  for (const k of Object.keys(cluster.total).sort()) {
    const used = (cluster.total[k] - (cluster.available[k] ?? 0));
    html += `<div class="card"><div class="num">` +
      `${+used.toFixed(2)}<span class="muted">/${cluster.total[k]}</span>` +
      `</div><div class="label">${esc(k)} used</div></div>`;
  }
  html += "</div><h2>Metrics</h2><div class='sparkrow'>";
  for (const [name, pts] of Object.entries(ts.series))
    html += spark(pts, name);
  html += "</div>";
  $("#page").innerHTML = html;
};

PAGES.nodes = tablePage("nodes", "Nodes");
PAGES.workers = tablePage("workers", "Workers");
PAGES.actors = tablePage("actors", "Actors");
PAGES.tasks = tablePage("tasks", "Tasks");
PAGES.placement_groups = tablePage("placement_groups", "Placement groups");
PAGES.objects = tablePage("objects", "Objects");

PAGES.task = async (tid) => {
  const d = await getJSON(`/api/task/${encodeURIComponent(tid)}`);
  $("#page").innerHTML = `<h1>Task <code>${esc(tid)}</code></h1>` +
    "<h2>State</h2><pre>" + esc(JSON.stringify(d.task, null, 2)) + "</pre>" +
    `<h2>Timeline events (${d.events.length})</h2>` +
    renderTable("events", d.events, "");
};

PAGES.actor = async (aid) => {
  const d = await getJSON(`/api/actor/${encodeURIComponent(aid)}`);
  $("#page").innerHTML = `<h1>Actor <code>${esc(aid)}</code></h1>` +
    "<h2>State</h2><pre>" + esc(JSON.stringify(d.actor, null, 2)) + "</pre>" +
    `<h2>Tasks (${d.tasks.length})</h2>` + renderTable("tasks", d.tasks, "");
};

PAGES.jobs = async () => {
  const jobs = await getJSON("/api/jobs");
  $("#page").innerHTML =
    `<h1>Jobs <span class="muted">(${jobs.length})</span></h1>` +
    renderTable("jobs", jobs, "") +
    '<p class="muted">submit via <code>ray_tpu job submit -- ' +
    "&lt;cmd&gt;</code></p>";
};

PAGES.serve = async () => {
  const apps = await getJSON("/api/serve/applications/");
  const names = Object.keys(apps);
  let html = `<h1>Serve <span class="muted">(${names.length} apps)</span></h1>`;
  if (!names.length) html += '<p class="muted">serve not running</p>';
  for (const name of names) {
    const a = apps[name];
    html += `<h2>${esc(name)} <span class="status ${statusClass(a.status)}">` +
      `${esc(a.status)}</span> <code>${esc(a.route_prefix ?? "")}</code></h2>`;
    const deps = Object.entries(a.deployments).map(([d, s]) => ({
      deployment: d, status: s.status, replicas: s.num_replicas,
      message: s.message,
    }));
    html += renderTable("deployments", deps, "");
  }
  $("#page").innerHTML = html;
};

PAGES.logs = async () => {
  const prefix = tableFilters.__logprefix || "";
  const logs = await getJSON(
    `/api/logs?tail=300&prefix=${encodeURIComponent(prefix)}`);
  // Preserve the reading position across refreshes: follow the tail
  // only when pinned at the bottom, else restore the exact offset.
  const prev = $("#logpre");
  const atBottom = !prev ||
    prev.scrollTop + prev.clientHeight >= prev.scrollHeight - 4;
  const prevTop = prev ? prev.scrollTop : 0;
  $("#page").innerHTML = "<h1>Logs</h1>" +
    `<div class="toolbar"><input id="prefix" placeholder="worker prefix…" ` +
    `value="${esc(prefix)}"></div>` +
    `<pre id="logpre">` + logs.lines.map((l) =>
      esc(`[${l[0]}|${String(l[1]).slice(0, 8)}] ${l[2]}`)).join("\n") +
    "</pre>";
  const pre = $("#logpre");
  pre.scrollTop = atBottom ? pre.scrollHeight : prevTop;
  $("#prefix").addEventListener("change", (e) => {
    tableFilters.__logprefix = e.target.value;
    route();
  });
};

// ------------------------------------------------------ flamegraph page

function parseFolded(text) {
  // "a;b;c 12" lines -> trie with per-node inclusive counts.
  const root = { name: "all", value: 0, children: new Map() };
  for (const line of text.split("\n")) {
    if (!line || line.startsWith("#")) continue;
    const sp = line.lastIndexOf(" ");
    if (sp < 0) continue;
    const count = parseInt(line.slice(sp + 1), 10);
    if (!Number.isFinite(count)) continue;
    root.value += count;
    let node = root;
    for (const frame of line.slice(0, sp).split(";")) {
      let child = node.children.get(frame);
      if (!child) {
        child = { name: frame, value: 0, children: new Map() };
        node.children.set(frame, child);
      }
      child.value += count;
      node = child;
    }
  }
  return root;
}

const FLAME_COLORS = [
  "#d9734f", "#e0975a", "#c75146", "#e3b25f", "#d98a68", "#c9653b",
];
function flameColor(name) {
  let h = 0;
  for (let i = 0; i < name.length; i++) h = (h * 31 + name.charCodeAt(i)) | 0;
  return FLAME_COLORS[Math.abs(h) % FLAME_COLORS.length];
}

function renderFlame(root, focus) {
  // focus: node to zoom to (occupies full width).
  const W = Math.max(600, $("#page").clientWidth - 20);
  const ROW = 18;
  focus = focus || root;
  let maxDepth = 0;
  (function depth(n, d) {
    maxDepth = Math.max(maxDepth, d);
    for (const c of n.children.values()) depth(c, d + 1);
  })(focus, 0);
  const H = (maxDepth + 1) * ROW;
  const rects = [];
  (function walk(node, x, w, d) {
    if (w < 1) return;
    const label = w > 40
      ? `<text x="${(x + 3).toFixed(1)}" y="${(H - d * ROW - 5).toFixed(1)}">` +
        esc(node.name.length > w / 7 ? node.name.slice(0, w / 7) + "…"
            : node.name) + "</text>"
      : "";
    rects.push(
      `<g data-path="${esc(node.__path)}" data-tip="${esc(node.name)} — ` +
      `${node.value} samples (${(100 * node.value / root.value).toFixed(1)}%)">` +
      `<rect x="${x.toFixed(1)}" y="${(H - (d + 1) * ROW).toFixed(1)}" ` +
      `width="${w.toFixed(1)}" height="${ROW - 1}" ` +
      `fill="${flameColor(node.name)}"/>${label}</g>`);
    let cx = x;
    for (const c of node.children.values()) {
      const cw = (c.value / node.value) * w;
      walk(c, cx, cw, d + 1);
      cx += cw;
    }
  })(focus, 0, W, 0);
  return `<svg id="flame" width="${W}" height="${H}" ` +
    `viewBox="0 0 ${W} ${H}">${rects.join("")}</svg>`;
}

function indexPaths(root) {
  (function walk(n, path) {
    n.__path = path;
    for (const c of n.children.values()) walk(c, path + ";" + c.name);
  })(root, root.name);
}

function findPath(root, path) {
  if (path === root.name) return root;
  let node = root;
  for (const part of path.split(";").slice(1)) {
    node = node.children.get(part);
    if (!node) return root;
  }
  return node;
}

PAGES.profile = async (wid) => {
  $("#page").innerHTML = `<h1>Profile <code>${esc(wid)}</code></h1>` +
    `<div class="toolbar">duration <select id="dur">` +
    ["2", "5", "10", "30"].map((d) =>
      `<option ${d === "5" ? "selected" : ""}>${d}</option>`).join("") +
    `</select>s <button id="go">sample</button> ` +
    `<a href="/api/profile/${encodeURIComponent(wid)}">live stacks</a> ` +
    `<span id="prof-status" class="muted"></span></div>` +
    `<div id="flamebox"></div><div id="flame-tip"></div>`;
  $("#go").addEventListener("click", async () => {
    $("#prof-status").textContent = "sampling…";
    try {
      const dur = $("#dur").value;
      const r = await fetch(
        `/api/profile/${encodeURIComponent(wid)}?mode=sample&duration=${dur}`);
      if (!r.ok) throw new Error(`HTTP ${r.status}: ${await r.text()}`);
      const root = parseFolded(await r.text());
      indexPaths(root);
      if (!root.value) {
        $("#flamebox").innerHTML = '<p class="muted">no samples</p>';
        $("#prof-status").textContent = "";
        return;
      }
      const draw = (focus) => {
        $("#flamebox").innerHTML = renderFlame(root, focus) +
          '<p class="muted">click a frame to zoom; click the base to reset</p>';
        $("#flame").addEventListener("click", (e) => {
          const g = e.target.closest("g[data-path]");
          if (!g) return;
          const node = findPath(root, g.dataset.path);
          draw(node === focus ? root : node);
        });
        $("#flame").addEventListener("mousemove", (e) => {
          const g = e.target.closest("g[data-tip]");
          const tip = $("#flame-tip");
          if (!g) { tip.style.display = "none"; return; }
          tip.textContent = g.dataset.tip;
          tip.style.display = "block";
          tip.style.left = Math.min(e.clientX + 12,
            window.innerWidth - 320) + "px";
          tip.style.top = (e.clientY + 12) + "px";
        });
        $("#flame").addEventListener("mouseleave", () => {
          $("#flame-tip").style.display = "none";
        });
      };
      draw(root);
      $("#prof-status").textContent = `${root.value} samples`;
    } catch (e) {
      $("#prof-status").textContent = "";
      $("#flamebox").innerHTML =
        `<div class="err-banner">${esc(e.message || e)}</div>`;
    }
  });
};
