"""Dashboard: HTTP view of cluster state.

Reference: dashboard/ (aiohttp head process serving a React frontend +
JSON APIs fed by the GCS and agents). Scoped-down equivalent: one
aiohttp actor serving the state API as JSON under /api/* plus a
self-contained HTML overview — the data pipeline (GCS task events →
state API) is the same one the reference's dashboard rides.

    from ray_tpu.dashboard import start_dashboard
    url = start_dashboard(port=8265)
"""
from __future__ import annotations

import json
from typing import Optional

_PAGE = """<!doctype html>
<html><head><title>ray_tpu dashboard</title>
<style>
 body { font-family: system-ui, sans-serif; margin: 2rem; color: #222; }
 h1 { font-size: 1.3rem; } h2 { font-size: 1.05rem; margin-top: 1.5rem; }
 table { border-collapse: collapse; margin-top: .5rem; }
 td, th { border: 1px solid #ccc; padding: .25rem .6rem; font-size: .85rem; }
 th { background: #f3f3f3; text-align: left; }
 code { background: #f6f6f6; padding: 0 .25rem; }
</style></head>
<body>
<h1>ray_tpu dashboard</h1>
<div id="root">loading…</div>
<script>
const KINDS = ["nodes", "workers", "actors", "tasks", "placement_groups"];
async function refresh() {
  const root = document.getElementById("root");
  let html = "";
  const res = await fetch("/api/cluster"); const cluster = await res.json();
  html += "<h2>Resources</h2><table><tr><th>resource</th><th>available</th><th>total</th></tr>";
  for (const k of Object.keys(cluster.total).sort())
    html += `<tr><td>${k}</td><td>${cluster.available[k] ?? 0}</td><td>${cluster.total[k]}</td></tr>`;
  html += "</table>";
  for (const kind of KINDS) {
    const r = await fetch(`/api/${kind}`); const items = await r.json();
    html += `<h2>${kind} (${items.length})</h2>`;
    if (!items.length) { html += "<p>(none)</p>"; continue; }
    const cols = Object.keys(items[0]);
    html += "<table><tr>" + cols.map(c => `<th>${c}</th>`).join("") + "</tr>";
    for (const it of items.slice(0, 50))
      html += "<tr>" + cols.map(c => `<td>${JSON.stringify(it[c])}</td>`).join("") + "</tr>";
    html += "</table>";
  }
  root.innerHTML = html;
}
refresh(); setInterval(refresh, 2000);
</script></body></html>
"""


class DashboardActor:
    def __init__(self, host: str, port: int):
        self._host = host
        self._port = port
        self._runner = None

    async def ready(self) -> str:
        if self._runner is not None:
            return f"http://{self._host}:{self._port}"
        from aiohttp import web

        app = web.Application()
        app.router.add_get("/", self._index)
        app.router.add_get("/api/cluster", self._cluster)
        app.router.add_get("/api/{kind}", self._list)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self._host, self._port)
        await site.start()
        return f"http://{self._host}:{self._port}"

    async def _index(self, request):
        from aiohttp import web

        return web.Response(text=_PAGE, content_type="text/html")

    async def _cluster(self, request):
        from aiohttp import web

        import ray_tpu

        return web.json_response(
            {
                "total": ray_tpu.cluster_resources(),
                "available": ray_tpu.available_resources(),
            }
        )

    async def _list(self, request):
        from aiohttp import web

        from ..util import state as state_api

        kind = request.match_info["kind"]
        fn = getattr(state_api, f"list_{kind}", None)
        if fn is None:
            return web.Response(status=404, text=f"unknown kind {kind}")
        return web.json_response(fn(limit=500))

    async def shutdown(self):
        if self._runner:
            await self._runner.cleanup()


def start_dashboard(host: str = "127.0.0.1", port: int = 8265) -> str:
    """Start (or find) the dashboard actor; returns its URL."""
    import ray_tpu

    actor = (
        ray_tpu.remote(DashboardActor)
        .options(name="RAY_TPU_DASHBOARD", max_concurrency=16,
                 get_if_exists=True, num_cpus=0)
        .remote(host, port)
    )
    return ray_tpu.get(actor.ready.remote())
