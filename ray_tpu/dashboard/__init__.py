"""Dashboard: HTTP view of cluster state, metrics, logs, profiling.

Reference: dashboard/ (aiohttp head process serving a React frontend +
JSON APIs fed by the GCS and agents). Equivalent riding the same data
pipelines, with the frontend as a no-build-step static SPA
(``client/``: hash-routed pages for overview/nodes/workers/actors/
tasks/PGs/objects/jobs/serve/logs plus an SVG flamegraph viewer —
reference ``dashboard/client/src``, matched in function not pixels):

  /                         the SPA shell (client/index.html)
  /static/{app.js,style.css} SPA assets
  /api/cluster              resources
  /api/{nodes,workers,...}  state API as JSON
  /api/metrics_timeseries   ring buffer of sampled core gauges
  /api/logs?prefix=&tail=   the driver log ring (log pipeline)
  /api/profile/{worker_id}  live thread stacks from a worker;
                            ?mode=sample&duration=5 returns a
                            statistical profile as folded flamegraph
                            stacks (reference:
                            reporter/profile_manager.py py-spy -f —
                            in-process sampling instead of ptrace)
  /metrics                  Prometheus text exposition of user +
                            core-runtime metrics (reference: the node
                            metrics agent's Prometheus endpoint)
  /api/serve/applications/  GET live app statuses / PUT a declarative
                            config (deploys it) / DELETE all apps
                            (reference: dashboard/modules/serve/ REST
                            config API)
  /api/workflow/events/{k}  POST fires a workflow event (reference:
                            workflow/http_event_provider.py)
  /api/task/{task_id}       one task's state + its timeline events
  /api/actor/{actor_id}     one actor's state + its tasks
  /api/jobs                 submitted jobs (job_submission KV table)
  /api/job/{job_id}/logs    one job's captured output

    from ray_tpu.dashboard import start_dashboard
    url = start_dashboard(port=8265)
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Optional

_CLIENT_DIR = os.path.join(os.path.dirname(__file__), "client")

# Core gauges sampled into the timeseries ring (2s period, ~10min of
# history at 300 samples).
_SAMPLE_PERIOD_S = 2.0
_RING = 300


class DashboardActor:
    def __init__(self, host: str, port: int):
        self._host = host
        self._port = port
        self._runner = None
        self._ts: dict = {}  # name -> deque[(t, value)]
        # Sampler runs in a to_thread worker while handlers iterate on
        # the event loop — guard both.
        self._ts_lock = threading.Lock()
        self._sampler = None

    async def ready(self) -> str:
        if self._runner is not None:
            return f"http://{self._host}:{self._port}"
        import asyncio

        from aiohttp import web

        app = web.Application()
        app.router.add_get("/", self._index)
        app.router.add_get("/api/cluster", self._cluster)
        app.router.add_get("/api/metrics_timeseries", self._timeseries)
        app.router.add_get("/api/logs", self._logs)
        app.router.add_get("/api/profile/{worker_id}", self._profile)
        app.router.add_get("/metrics", self._prometheus)
        app.router.add_get(
            "/api/serve/applications/", self._serve_get
        )
        app.router.add_put(
            "/api/serve/applications/", self._serve_put
        )
        app.router.add_delete(
            "/api/serve/applications/", self._serve_delete
        )
        app.router.add_post(
            "/api/workflow/events/{key:.+}", self._workflow_event
        )
        app.router.add_get("/api/events", self._events)
        app.router.add_get("/api/events_summary", self._events_summary)
        app.router.add_get("/api/task/{task_id}", self._task_detail)
        app.router.add_get("/api/actor/{actor_id}", self._actor_detail)
        app.router.add_get("/api/jobs", self._jobs)
        app.router.add_get("/api/job/{job_id}/logs", self._job_logs)
        app.router.add_get("/api/{kind}", self._list)
        app.router.add_get("/static/{name}", self._static)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self._host, self._port)
        await site.start()
        self._sampler = asyncio.ensure_future(self._sample_loop())
        return f"http://{self._host}:{self._port}"

    # -------------------------------------------------------- timeseries
    def _sample_once(self):
        import ray_tpu

        total = ray_tpu.cluster_resources()
        avail = ray_tpu.available_resources()
        now = time.time()
        samples = {}
        for k, v in total.items():
            samples[f"{k} used"] = v - avail.get(k, 0.0)
        nodes = ray_tpu.nodes()
        samples["nodes alive"] = float(
            sum(1 for n in nodes if n["alive"])
        )
        # Per-node CPU drill-down series (reference: per-node charts in
        # the dashboard frontend).
        for n in nodes:
            if not n["alive"]:
                continue
            # Labels default to the hostname, which co-hosted nodes
            # share: suffix a node-id tag so series never collapse.
            label = n.get("label") or "node"
            tag = n["node_id"].hex()[:6]
            used = n["total"].get("CPU", 0.0) - n["available"].get("CPU", 0.0)
            samples[f"CPU used @ {label}:{tag}"] = used
        from ..util.state import list_workers

        samples["workers"] = float(len(list_workers(limit=10_000)))
        with self._ts_lock:
            for name, v in samples.items():
                self._ts.setdefault(name, deque(maxlen=_RING)).append(
                    (now, v)
                )

    async def _sample_loop(self):
        import asyncio

        while True:
            try:
                # Off the event loop: the sample does blocking GCS RPCs.
                await asyncio.to_thread(self._sample_once)
            except Exception:  # noqa: BLE001 - cluster may be mid-shutdown
                pass
            await asyncio.sleep(_SAMPLE_PERIOD_S)

    async def _timeseries(self, request):
        from aiohttp import web

        with self._ts_lock:
            series = {
                name: [v for _, v in dq] for name, dq in self._ts.items()
            }
            stamps = {
                name: [t for t, _ in dq] for name, dq in self._ts.items()
            }
        return web.json_response(
            {
                "period_s": _SAMPLE_PERIOD_S,
                "series": series,
                "timestamps": stamps,
            }
        )

    # ------------------------------------------------------------- pages
    async def _index(self, request):
        from aiohttp import web

        with open(os.path.join(_CLIENT_DIR, "index.html")) as f:
            return web.Response(text=f.read(), content_type="text/html")

    async def _static(self, request):
        from aiohttp import web

        name = request.match_info["name"]
        # Flat directory, explicit allowlist: no traversal surface.
        types = {"app.js": "application/javascript", "style.css": "text/css"}
        if name not in types:
            return web.Response(status=404, text=f"no asset {name}")
        with open(os.path.join(_CLIENT_DIR, name)) as f:
            return web.Response(text=f.read(), content_type=types[name])

    async def _cluster(self, request):
        from aiohttp import web

        import ray_tpu

        return web.json_response(
            {
                "total": ray_tpu.cluster_resources(),
                "available": ray_tpu.available_resources(),
            }
        )

    async def _list(self, request):
        import asyncio

        from aiohttp import web

        from ..util import state as state_api

        kind = request.match_info["kind"]
        fn = getattr(state_api, f"list_{kind}", None)
        if fn is None:
            return web.Response(status=404, text=f"unknown kind {kind}")
        return web.json_response(await asyncio.to_thread(fn, limit=500))

    # -------------------------------------------------------------- logs
    async def _logs(self, request):
        import asyncio

        from aiohttp import web

        from .._private.worker import global_client

        reply = await asyncio.to_thread(
            global_client().request,
            {
                "type": "get_logs",
                "worker_prefix": request.query.get("prefix", ""),
                "tail": int(request.query.get("tail", 200)),
            },
        )
        return web.json_response({"lines": reply.get("lines", [])})

    # ----------------------------------------------------------- profile
    async def _profile(self, request):
        import asyncio

        from aiohttp import web

        from .._private.worker import global_client

        wid = bytes.fromhex(request.match_info["worker_id"])
        if request.query.get("mode") == "sample":
            # Statistical profile: folded flamegraph stacks, ready for
            # speedscope / flamegraph.pl.
            try:
                duration = float(request.query.get("duration", "5"))
            except ValueError:
                duration = 5.0
            if not (duration == duration):  # NaN
                duration = 5.0
            duration = min(max(duration, 0.1), 60.0)
            reply = await asyncio.to_thread(
                global_client().request,
                {
                    "type": "worker_profile",
                    "worker_id": wid,
                    "duration": duration,
                    "interval": float(
                        request.query.get("interval", "0.01")
                    ),
                },
                duration + 15.0,
            )
            if not reply.get("ok"):
                return web.Response(
                    status=404, text=reply.get("error", "?")
                )
            header = (
                f"# folded stacks: {reply.get('samples')} samples over "
                f"{duration}s\n"
            )
            return web.Response(
                text=header + reply["text"], content_type="text/plain"
            )
        # The GCS waiter can take up to its 10s sweep to time out —
        # never hold the event loop for that.
        reply = await asyncio.to_thread(
            global_client().request,
            {"type": "worker_stacks", "worker_id": wid},
            15.0,
        )
        if not reply.get("ok"):
            return web.Response(status=404, text=reply.get("error", "?"))
        return web.Response(text=reply["text"], content_type="text/plain")

    # ------------------------------------------------------------- serve
    def _serve_statuses_json(self):
        from .. import serve

        out = {}
        for name, info in serve.status().items():
            out[name] = {
                "status": info.status.value,
                "message": info.message,
                "route_prefix": info.route_prefix,
                "deployments": {
                    d: {
                        "status": s.status.value,
                        "message": s.message,
                        "num_replicas": s.num_replicas,
                    }
                    for d, s in info.deployments.items()
                },
            }
        return out

    async def _serve_get(self, request):
        import asyncio

        from aiohttp import web

        try:
            return web.json_response(
                await asyncio.to_thread(self._serve_statuses_json)
            )
        except ValueError:  # controller actor not found: serve not started
            return web.json_response({})

    async def _serve_put(self, request):
        """Declarative deploy over HTTP: the same schema as `serve
        deploy config.yaml` (reference: dashboard/modules/serve PUT
        /api/serve/applications/)."""
        import asyncio

        from aiohttp import web

        from ..serve.schema import deploy_config

        def deploy(config):
            deploy_config(config, _blocking=True)
            return self._serve_statuses_json()

        try:
            config = await request.json()
            return web.json_response(
                await asyncio.to_thread(deploy, config)
            )
        except Exception as e:  # noqa: BLE001 - bad body/config -> 400
            return web.json_response(
                {"error": f"{type(e).__name__}: {e}"}, status=400
            )

    async def _serve_delete(self, request):
        import asyncio

        from aiohttp import web

        from .. import serve

        await asyncio.to_thread(serve.shutdown)
        return web.Response(status=204)

    async def _workflow_event(self, request):
        """HTTP event provider (reference: workflow/
        http_event_provider.py): POST a JSON payload to fire the event
        any waiting workflow node resolves to."""
        import asyncio

        from aiohttp import web

        from ..workflow import post_event

        key = request.match_info["key"]
        try:
            payload = await request.json() if request.can_read_body else None
        except Exception as e:  # noqa: BLE001 - malformed body -> 400
            return web.json_response(
                {"error": f"{type(e).__name__}: {e}"}, status=400
            )
        await asyncio.to_thread(post_event, key, payload)
        return web.json_response({"ok": True, "key": key})

    # --------------------------------------------------------------- jobs
    async def _jobs(self, request):
        """Submitted jobs (reference: dashboard/modules/job/ — the job
        head serves the submission table the SDK writes)."""
        import asyncio

        from aiohttp import web

        from ..job_submission import JobSubmissionClient

        # No swallow: an empty table already returns [] — any exception
        # here is a real failure and must surface as a 500, not render
        # as a healthy empty jobs list.
        return web.json_response(
            await asyncio.to_thread(lambda: JobSubmissionClient().list_jobs())
        )

    async def _job_logs(self, request):
        import asyncio

        from aiohttp import web

        from ..job_submission import JobSubmissionClient

        jid = request.match_info["job_id"]
        text = await asyncio.to_thread(
            lambda: JobSubmissionClient().get_job_logs(jid)
        )
        return web.Response(text=text, content_type="text/plain")

    # ------------------------------------------------------------- events
    async def _events(self, request):
        """Flight-recorder feed (events.py): runtime transitions for
        the timeline view, filterable by task id / category."""
        import asyncio

        from aiohttp import web

        from ..util.state import list_cluster_events

        q = request.query
        try:
            limit = int(q.get("limit", "500"))
        except ValueError:
            limit = 500
        events = await asyncio.to_thread(
            list_cluster_events,
            entity=q.get("task") or None,
            category=q.get("category") or None,
            limit=limit,
        )
        return web.json_response({"events": events})

    async def _events_summary(self, request):
        """Derived flight-recorder metrics as JSON: per-phase latency
        histograms, drop counters, queue depth — the same numbers the
        /metrics Prometheus series are built from."""
        import asyncio

        from aiohttp import web

        from ..util.state import summarize_events

        summary = await asyncio.to_thread(summarize_events)
        return web.json_response({"summary": summary})

    # --------------------------------------------------------- drill-down
    async def _task_detail(self, request):
        import asyncio

        from aiohttp import web

        from .._private import state as _state
        from ..util.state import list_tasks

        tid = request.match_info["task_id"]

        def build():
            # EXACT match only: ids are process-prefix + counter, so a
            # truncated prefix matches every id from that driver.
            rows = [
                t for t in list_tasks(limit=10_000)
                if t.get("task_id", "") == tid
            ]
            events = [
                e for e in _state.task_events()
                if e.get("task_id", "") == tid
            ]
            return {"task": rows[0] if rows else None, "events": events}

        detail = await asyncio.to_thread(build)
        if detail["task"] is None and not detail["events"]:
            return web.Response(status=404, text=f"no task {tid}")
        return web.json_response(detail)

    async def _actor_detail(self, request):
        import asyncio

        from aiohttp import web

        from ..util.state import list_actors, list_tasks

        aid = request.match_info["actor_id"]

        def build():
            rows = [
                a for a in list_actors(limit=10_000)
                if a.get("actor_id", "") == aid
            ]
            tasks = [
                t for t in list_tasks(limit=10_000)
                if t.get("actor_id", "") == aid
            ]
            return {"actor": rows[0] if rows else None, "tasks": tasks}

        detail = await asyncio.to_thread(build)
        if detail["actor"] is None:
            return web.Response(status=404, text=f"no actor {aid}")
        return web.json_response(detail)

    # -------------------------------------------------------- prometheus
    async def _prometheus(self, request):
        import asyncio

        from aiohttp import web

        from ..util.metrics import (
            core_runtime_snapshot,
            get_metrics_snapshot,
            prometheus_text,
        )

        def scrape() -> str:
            snap = get_metrics_snapshot()
            try:
                snap.update(core_runtime_snapshot())
            except Exception:  # noqa: BLE001 - keep user metrics
                pass
            return prometheus_text(snap)

        return web.Response(
            text=await asyncio.to_thread(scrape),
            content_type="text/plain",
            charset="utf-8",
        )

    async def shutdown(self):
        if self._sampler:
            self._sampler.cancel()
        if self._runner:
            await self._runner.cleanup()


def start_dashboard(host: str = "127.0.0.1", port: int = 8265) -> str:
    """Start (or find) the dashboard actor; returns its URL."""
    import ray_tpu

    actor = (
        ray_tpu.remote(DashboardActor)
        .options(name="RAY_TPU_DASHBOARD", max_concurrency=16,
                 get_if_exists=True, num_cpus=0)
        .remote(host, port)
    )
    return ray_tpu.get(actor.ready.remote())
