"""Connector-v2: composable transforms between env, module, and learner.

Reference: rllib/connectors/connector_v2.py:18 +
connector_pipeline_v2.py:18. Three pipeline positions:
env-to-module (raw observations → inference batch), module-to-env
(module outputs → env actions), and learner (episodes → train batch).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np


class ConnectorV2:
    def __call__(self, *, rl_module=None, batch=None, episodes=None, **kwargs):
        raise NotImplementedError


class ConnectorPipelineV2(ConnectorV2):
    def __init__(self, connectors: Optional[List[ConnectorV2]] = None):
        self.connectors = list(connectors or [])

    def append(self, connector: ConnectorV2) -> "ConnectorPipelineV2":
        self.connectors.append(connector)
        return self

    def prepend(self, connector: ConnectorV2) -> "ConnectorPipelineV2":
        self.connectors.insert(0, connector)
        return self

    def __call__(self, *, rl_module=None, batch=None, episodes=None, **kwargs):
        for c in self.connectors:
            batch = c(rl_module=rl_module, batch=batch, episodes=episodes, **kwargs)
        return batch


# ----------------------------------------------------------- env-to-module
class BatchObservations(ConnectorV2):
    """Stack per-env current observations into the inference batch
    (reference: AddObservationsFromEpisodesToBatch + BatchIndividualItems)."""

    def __call__(self, *, rl_module=None, batch=None, episodes=None, **kwargs):
        obs = np.stack([np.asarray(ep.observations[-1]) for ep in episodes])
        return {"obs": obs.astype(np.float32)}


# ----------------------------------------------------------- module-to-env
class SampleCategoricalActions(ConnectorV2):
    """Sample discrete actions from logits; record logp so PPO's loss
    can importance-weight (reference: GetActions + action-dist
    connectors)."""

    def __init__(self, explore: bool = True, rng: Optional[np.random.Generator] = None):
        self.explore = explore
        self.rng = rng or np.random.default_rng()

    def __call__(self, *, rl_module=None, batch=None, episodes=None, **kwargs):
        logits = np.asarray(batch["action_dist_inputs"], np.float32)
        z = logits - logits.max(axis=-1, keepdims=True)
        logp_all = z - np.log(np.exp(z).sum(axis=-1, keepdims=True))
        if kwargs.get("explore", self.explore):
            # Gumbel-max sampling, vectorized over envs.
            g = self.rng.gumbel(size=logits.shape)
            actions = np.argmax(logits + g, axis=-1)
        else:
            actions = np.argmax(logits, axis=-1)
        logp = np.take_along_axis(logp_all, actions[:, None], axis=-1)[:, 0]
        batch["actions"] = actions
        batch["action_logp"] = logp.astype(np.float32)
        return batch


class EpsilonGreedyActions(ConnectorV2):
    """ε-greedy over Q-values for value-based algorithms (DQN)."""

    def __init__(self, epsilon_fn, rng: Optional[np.random.Generator] = None):
        self.epsilon_fn = epsilon_fn  # step -> epsilon
        self.rng = rng or np.random.default_rng()
        self.step = 0

    def __call__(self, *, rl_module=None, batch=None, episodes=None, **kwargs):
        q = np.asarray(batch["q_values"] if "q_values" in batch
                       else batch["action_dist_inputs"])
        eps = (
            self.epsilon_fn(self.step)
            if kwargs.get("explore", True)
            else 0.0
        )
        self.step += q.shape[0]
        greedy = np.argmax(q, axis=-1)
        random = self.rng.integers(0, q.shape[-1], size=q.shape[0])
        mask = self.rng.random(q.shape[0]) < eps
        batch["actions"] = np.where(mask, random, greedy)
        return batch


# --------------------------------------------------------------- learner
class EpisodesToBatch(ConnectorV2):
    """Concatenate finalized episodes into one flat train batch with
    per-timestep columns (reference: learner pipeline batching)."""

    def __call__(self, *, rl_module=None, batch=None, episodes=None, **kwargs):
        out: Dict[str, Any] = {
            "obs": np.concatenate([ep.observations[:-1] for ep in episodes]),
            "next_obs": np.concatenate([ep.observations[1:] for ep in episodes]),
            "actions": np.concatenate([ep.actions for ep in episodes]),
            "rewards": np.concatenate([ep.rewards for ep in episodes]),
            "terminateds": np.concatenate(
                [
                    _done_mask(len(ep), ep.is_terminated)
                    for ep in episodes
                ]
            ),
        }
        for key in episodes[0].extra_model_outputs:
            out[key] = np.concatenate(
                [ep.extra_model_outputs[key] for ep in episodes]
            )
        out["obs"] = out["obs"].astype(np.float32)
        out["next_obs"] = out["next_obs"].astype(np.float32)
        return out


class GeneralAdvantageEstimation(ConnectorV2):
    """GAE(λ) per episode, appended as advantages/value_targets columns
    (reference: rllib/connectors/learner/general_advantage_estimation.py)."""

    def __init__(self, gamma: float = 0.99, lambda_: float = 0.95,
                 values_fn=None):
        self.gamma = gamma
        self.lambda_ = lambda_
        # (list of obs[T_i+1, ...]) -> list of values[T_i+1]; batched so
        # the value net runs ONE jitted call for all episodes instead of
        # one XLA compile per episode length.
        self.values_fn = values_fn

    def __call__(self, *, rl_module=None, batch=None, episodes=None, **kwargs):
        obs_list = [np.asarray(ep.observations, np.float32) for ep in episodes]
        values_list = self.values_fn(obs_list)
        advantages, targets, vf_preds = [], [], []
        for ep, values in zip(episodes, values_list):
            values = np.asarray(values, np.float32)
            rewards = np.asarray(ep.rewards, np.float32)
            T = len(rewards)
            # Bootstrap value is 0 at true terminations, V(s_T) otherwise.
            last_v = 0.0 if ep.is_terminated else float(values[T])
            adv = np.zeros(T, np.float32)
            gae = 0.0
            for t in range(T - 1, -1, -1):
                next_v = last_v if t == T - 1 else values[t + 1]
                delta = rewards[t] + self.gamma * next_v - values[t]
                gae = delta + self.gamma * self.lambda_ * gae
                adv[t] = gae
            advantages.append(adv)
            targets.append(adv + values[:T])
            vf_preds.append(values[:T])
        batch = dict(batch or {})
        batch["advantages"] = np.concatenate(advantages)
        batch["value_targets"] = np.concatenate(targets)
        batch["vf_preds"] = np.concatenate(vf_preds)
        return batch


def _done_mask(length: int, terminated: bool) -> np.ndarray:
    m = np.zeros(length, np.float32)
    if terminated and length:
        m[-1] = 1.0
    return m
