"""SingleAgentEnvRunner: samples episodes from gymnasium vector envs.

Reference: rllib/env/single_agent_env_runner.py — the hot rollout loop:
vectorized env.step + module forward per tick. Runs on CPU actors; the
module's forward uses jax-on-CPU with numpy weights pushed from the
learner (weight sync, env_runner_group.py:522).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..connectors.connector_v2 import (
    BatchObservations,
    ConnectorPipelineV2,
    SampleCategoricalActions,
)
from .episode import SingleAgentEpisode


def _make_env(env_spec, env_config):
    import gymnasium as gym

    if callable(env_spec):
        return env_spec(env_config)
    return gym.make(env_spec, **(env_config or {}))


class SingleAgentEnvRunner:
    """One actor; ``sample()`` returns finalized episode chunks."""

    def __init__(self, config_blob: bytes, worker_index: int = 0):
        import pickle

        cfg = pickle.loads(config_blob)
        self.config = cfg
        self.worker_index = worker_index
        self.num_envs = cfg["num_envs_per_env_runner"]
        seed = (cfg.get("seed") or 0) + 1000 * worker_index
        self._rng = np.random.default_rng(seed)

        import gymnasium as gym

        self.env = gym.vector.SyncVectorEnv(
            [
                (lambda i=i: _make_env(cfg["env"], cfg.get("env_config")))
                for i in range(self.num_envs)
            ]
        )
        spec = cfg["module_spec"]
        if spec.observation_space is None:
            spec.observation_space = self.env.single_observation_space
        if spec.action_space is None:
            spec.action_space = self.env.single_action_space
        self.module = spec.build()
        import jax

        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            self.params = jax.device_get(
                self.module.init_params(jax.random.PRNGKey(seed))
            )
        self._jit_forward = None

        self.env_to_module = cfg.get("env_to_module") or ConnectorPipelineV2(
            [BatchObservations()]
        )
        self.module_to_env = cfg.get("module_to_env") or ConnectorPipelineV2(
            [SampleCategoricalActions(rng=self._rng)]
        )
        self._episodes: List[SingleAgentEpisode] = []
        self._obs = None
        self._total_steps = 0
        # gymnasium >=1.0 vector envs use NEXT-step autoreset: the step
        # after a termination returns the reset observation with reward
        # 0 and ignores the action. Track which slots are in that state
        # so the bogus transition is dropped and the new episode starts
        # from the true reset obs.
        self._pending_reset = np.zeros(self.num_envs, bool)
        # True episode returns (accumulated across chunk cuts — a chunk's
        # sum undercounts episodes spanning sample boundaries).
        self._return_acc = np.zeros(self.num_envs, np.float64)
        self._completed_returns: List[float] = []

    # ------------------------------------------------------------ weights
    def set_weights(self, weights) -> None:
        self.params = weights

    def get_weights(self):
        return self.params

    # ------------------------------------------------------------- sample
    def _forward(self, batch: Dict[str, Any]) -> Dict[str, Any]:
        import jax

        if self._jit_forward is None:
            self._jit_forward = jax.jit(self.module.forward_exploration)
        # Rollouts stay on host CPU even when the process can see TPU
        # chips — the learner owns the accelerators.
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            out = self._jit_forward(self.params, batch)
        return {k: np.asarray(v) for k, v in out.items()}

    def _reset_if_needed(self):
        if self._obs is None:
            obs, _ = self.env.reset(seed=int(self._rng.integers(0, 2**31)))
            self._obs = obs
            self._episodes = [
                SingleAgentEpisode(initial_observation=obs[i])
                for i in range(self.num_envs)
            ]

    def sample(
        self,
        *,
        num_timesteps: Optional[int] = None,
        num_episodes: Optional[int] = None,
        explore: bool = True,
    ) -> List[SingleAgentEpisode]:
        """Collect at least num_timesteps env steps (across the vector
        env) or num_episodes full episodes."""
        self._reset_if_needed()
        if num_timesteps is None and num_episodes is None:
            num_timesteps = self.config.get("rollout_fragment_length", 200) * (
                self.num_envs
            )
        done_eps: List[SingleAgentEpisode] = []
        steps = 0
        while True:
            batch = self.env_to_module(episodes=self._episodes)
            outs = self._forward(batch)
            outs = self.module_to_env(
                batch=outs, episodes=self._episodes, explore=explore
            )
            actions = np.asarray(outs["actions"])
            obs, rewards, terms, truncs, _ = self.env.step(actions)
            extra_keys = [k for k in ("action_logp",) if k in outs]
            recorded = 0
            for i, ep in enumerate(self._episodes):
                if self._pending_reset[i]:
                    # This step performed the autoreset: obs[i] is the
                    # new episode's first observation; the transition is
                    # fake (action ignored, reward 0) — drop it.
                    self._episodes[i] = SingleAgentEpisode(
                        initial_observation=obs[i]
                    )
                    self._pending_reset[i] = False
                    continue
                self._return_acc[i] += rewards[i]
                recorded += 1
                ep.add_env_step(
                    obs[i],
                    actions[i],
                    rewards[i],
                    terminated=bool(terms[i]),
                    truncated=bool(truncs[i]),
                    extra_model_outputs={k: outs[k][i] for k in extra_keys},
                )
                if ep.is_done:
                    self._completed_returns.append(float(self._return_acc[i]))
                    self._return_acc[i] = 0.0
                    done_eps.append(ep.finalize())
                    # Placeholder until the autoreset step delivers the
                    # real initial observation (never recorded into).
                    self._episodes[i] = SingleAgentEpisode(
                        initial_observation=obs[i]
                    )
                    self._pending_reset[i] = True
            self._obs = obs
            steps += recorded
            self._total_steps += recorded
            if num_episodes is not None:
                if len(done_eps) >= num_episodes:
                    return done_eps[:num_episodes]
            elif steps >= num_timesteps:
                # Ship unfinished episodes as truncated chunks so the
                # learner sees exactly this sample's experience.
                out = list(done_eps)
                for i, ep in enumerate(self._episodes):
                    if len(ep) > 0:
                        ep.is_truncated = True
                        out.append(ep.finalize())
                        self._episodes[i] = SingleAgentEpisode(
                            initial_observation=np.asarray(ep.observations[-1])
                        )
                return out

    def stats(self) -> Dict[str, Any]:
        return {"total_env_steps": self._total_steps,
                "worker_index": self.worker_index}

    def get_metrics(self) -> Dict[str, Any]:
        """Completed-episode returns since last call (drained)."""
        out = {"episode_returns": self._completed_returns}
        self._completed_returns = []
        return out

    def ping(self) -> str:
        return "ok"
