"""MultiAgentEnvRunner: samples per-agent episodes from a MultiAgentEnv.

Reference: rllib/env/multi_agent_env_runner.py — one (non-vectorized)
multi-agent env per runner; each tick groups the live agents by the
module their policy_mapping_fn assigns, forwards each module once on
its group's stacked observations, and scatters sampled actions back
into the env's action dict. Output is per-agent SingleAgentEpisode
chunks tagged with ``module_id`` so the learner side can route each
trajectory to its policy's learner.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..connectors.connector_v2 import (
    ConnectorPipelineV2,
    SampleCategoricalActions,
)
from .episode import SingleAgentEpisode


class MultiAgentEnvRunner:
    def __init__(self, config_blob: bytes, worker_index: int = 0):
        import pickle

        cfg = pickle.loads(config_blob)
        self.config = cfg
        self.worker_index = worker_index
        seed = (cfg.get("seed") or 0) + 1000 * worker_index
        self._rng = np.random.default_rng(seed)
        env_spec = cfg["env"]
        assert callable(env_spec), (
            "multi-agent env must be a callable env maker"
        )
        self.env = env_spec(cfg.get("env_config") or {})
        self.policy_mapping_fn = cfg["policy_mapping_fn"]

        spec = cfg["module_spec"]  # MultiRLModuleSpec
        for mid, mspec in spec.module_specs.items():
            if mspec.observation_space is None or mspec.action_space is None:
                aid = self._agent_for_module(mid)
                mspec.observation_space = self.env.observation_space(aid)
                mspec.action_space = self.env.action_space(aid)
        self.module = spec.build()
        import jax

        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            self.params = jax.device_get(
                self.module.init_params(jax.random.PRNGKey(seed))
            )
        self._jit_forward: Dict[str, Any] = {}
        self.module_to_env = cfg.get("module_to_env") or ConnectorPipelineV2(
            [SampleCategoricalActions(rng=self._rng)]
        )

        self._obs: Optional[Dict[str, Any]] = None
        self._episodes: Dict[str, SingleAgentEpisode] = {}
        self._total_steps = 0
        self._return_acc: Dict[str, float] = {}
        # Rewards delivered on ticks where the agent had no action
        # (turn-based envs) — credited to the agent's next recorded
        # step so the trajectory's reward stream stays complete.
        self._pending_rew: Dict[str, float] = {}
        self._completed_returns: List[float] = []
        self._module_returns: Dict[str, List[float]] = {}

    def _agent_for_module(self, module_id: str) -> str:
        for aid in self.env.possible_agents:
            if self.policy_mapping_fn(aid) == module_id:
                return aid
        raise ValueError(f"no agent maps to module {module_id!r}")

    # ----------------------------------------------------------- weights
    def set_weights(self, weights: Dict[str, Any]) -> None:
        self.params.update(weights)

    def get_weights(self) -> Dict[str, Any]:
        return self.params

    # ------------------------------------------------------------ sample
    def _forward_module(self, module_id: str, obs: np.ndarray):
        import jax

        if module_id not in self._jit_forward:
            self._jit_forward[module_id] = jax.jit(
                self.module[module_id].forward_exploration
            )
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            out = self._jit_forward[module_id](
                self.params[module_id], {"obs": obs}
            )
        return {k: np.asarray(v) for k, v in out.items()}

    def _reset(self):
        obs, _ = self.env.reset(seed=int(self._rng.integers(0, 2**31)))
        self._obs = obs
        self._episodes = {
            aid: SingleAgentEpisode(initial_observation=o)
            for aid, o in obs.items()
        }
        self._return_acc = {aid: 0.0 for aid in obs}
        self._pending_rew = {}

    def sample(
        self,
        *,
        num_timesteps: Optional[int] = None,
        num_episodes: Optional[int] = None,
        explore: bool = True,
    ) -> List[SingleAgentEpisode]:
        """Collect env steps (one per tick regardless of agent count) or
        complete multi-agent episodes; returns per-agent chunks."""
        if self._obs is None:
            self._reset()
        if num_timesteps is None and num_episodes is None:
            num_timesteps = self.config.get("rollout_fragment_length", 200)
        done_eps: List[SingleAgentEpisode] = []
        completed_episodes = 0
        steps = 0
        while True:
            live = [aid for aid in self._obs if aid in self._episodes]
            by_module: Dict[str, List[str]] = {}
            for aid in live:
                by_module.setdefault(self.policy_mapping_fn(aid), []).append(
                    aid
                )
            action_dict: Dict[str, Any] = {}
            extras: Dict[str, Dict[str, Any]] = {}
            for mid, aids in by_module.items():
                obs = np.stack(
                    [np.asarray(self._obs[a], np.float32) for a in aids]
                )
                outs = self._forward_module(mid, obs)
                outs = self.module_to_env(
                    batch=outs, episodes=None, explore=explore
                )
                for i, aid in enumerate(aids):
                    action_dict[aid] = outs["actions"][i]
                    extras[aid] = {
                        k: outs[k][i]
                        for k in ("action_logp",)
                        if k in outs
                    }
            obs, rewards, terms, truncs, _ = self.env.step(action_dict)
            finished_now: set = set()
            for aid in action_dict:
                ep = self._episodes[aid]
                r = rewards.get(aid, 0.0) + self._pending_rew.pop(aid, 0.0)
                self._return_acc[aid] += r
                ep.add_env_step(
                    obs.get(aid, self._obs[aid]),
                    action_dict[aid],
                    r,
                    terminated=bool(terms.get(aid, False)),
                    truncated=bool(truncs.get(aid, False)),
                    extra_model_outputs=extras[aid],
                )
                if ep.is_done:
                    finished_now.add(aid)
                    done_eps.append(self._finish(aid, ep))
            # Rewards for agents that did not act this tick (turn-based
            # envs): accumulate into the return now, credit the reward
            # to the agent's next recorded step.
            for aid, r in rewards.items():
                if aid in action_dict or aid not in self._episodes:
                    continue
                self._return_acc[aid] = self._return_acc.get(aid, 0.0) + r
                self._pending_rew[aid] = self._pending_rew.get(aid, 0.0) + r
            # Agents appearing mid-episode get a fresh trajectory from
            # their first observation (the API allows agents to
            # appear/disappear between steps).
            for aid, o in obs.items():
                if aid not in self._episodes and aid not in finished_now:
                    self._episodes[aid] = SingleAgentEpisode(
                        initial_observation=o
                    )
                    self._return_acc.setdefault(aid, 0.0)
            self._obs = {
                aid: o for aid, o in obs.items() if aid in self._episodes
            }
            steps += 1
            self._total_steps += 1
            if terms.get("__all__") or truncs.get("__all__"):
                # Flush agents the env never individually terminated.
                for aid, ep in list(self._episodes.items()):
                    if len(ep) > 0:
                        ep.is_truncated = True
                        done_eps.append(self._finish(aid, ep))
                completed_episodes += 1
                self._reset()
            if num_episodes is not None:
                if completed_episodes >= num_episodes:
                    return done_eps
            elif steps >= num_timesteps:
                # Cut live episodes into shipped chunks.
                for aid, ep in list(self._episodes.items()):
                    if len(ep) > 0:
                        mid = self.policy_mapping_fn(aid)
                        chunk = ep.finalize()
                        chunk.module_id = mid
                        chunk.agent_id = aid
                        done_eps.append(chunk)
                        self._episodes[aid] = SingleAgentEpisode(
                            initial_observation=np.asarray(
                                chunk.observations[-1]
                            )
                        )
                return done_eps

    def _finish(self, aid: str, ep: SingleAgentEpisode) -> SingleAgentEpisode:
        mid = self.policy_mapping_fn(aid)
        # Credit any off-turn reward that never met another action step
        # to the final recorded step (the return already counted it).
        leftover = self._pending_rew.pop(aid, 0.0)
        if leftover and ep.rewards:
            ep.rewards[-1] += leftover
        ret = float(self._return_acc[aid])
        self._completed_returns.append(ret)
        self._module_returns.setdefault(mid, []).append(ret)
        self._return_acc[aid] = 0.0
        del self._episodes[aid]
        chunk = ep.finalize()
        chunk.module_id = mid
        chunk.agent_id = aid
        return chunk

    # ------------------------------------------------------------- misc
    def stats(self) -> Dict[str, Any]:
        return {
            "total_env_steps": self._total_steps,
            "worker_index": self.worker_index,
        }

    def get_metrics(self) -> Dict[str, Any]:
        out = {
            "episode_returns": self._completed_returns,
            "module_returns": self._module_returns,
        }
        self._completed_returns = []
        self._module_returns = {}
        return out

    def ping(self) -> str:
        return "ok"
