"""EnvRunnerGroup: the fleet of sampling actors.

Reference: rllib/env/env_runner_group.py (sync_weights :522). With
num_env_runners=0 a local runner samples in-process (debugging); with
N>0, N CPU actors sample in parallel and weights are broadcast through
the object store (one `put`, N handles).
"""
from __future__ import annotations

import pickle
from typing import Any, Dict, List, Optional

import ray_tpu

from ..utils.actor_manager import FaultTolerantActorManager
from .single_agent_env_runner import SingleAgentEnvRunner


class EnvRunnerGroup:
    def __init__(self, config: Dict[str, Any]):
        self._config = config
        self._blob = pickle.dumps(config)
        n = config.get("num_env_runners", 0)
        runner_cls = config.get("runner_cls") or SingleAgentEnvRunner
        self._local: Optional[SingleAgentEnvRunner] = None
        self._manager: Optional[FaultTolerantActorManager] = None
        if n == 0:
            self._local = runner_cls(self._blob, worker_index=0)
        else:
            actor_cls = ray_tpu.remote(runner_cls).options(
                num_cpus=config.get("num_cpus_per_env_runner", 1)
            )
            self._manager = FaultTolerantActorManager(
                lambda i: actor_cls.remote(self._blob, i + 1), n
            )

    @property
    def num_remote_runners(self) -> int:
        return self._manager.num_actors if self._manager else 0

    @property
    def num_healthy_env_runners(self) -> int:
        return self._manager.num_actors if self._manager else 1

    @property
    def num_restarts(self) -> int:
        return self._manager.num_restarts if self._manager else 0

    def sample(
        self, *, num_timesteps=None, num_episodes=None, explore=True
    ) -> List:
        if self._local is not None:
            return self._local.sample(
                num_timesteps=num_timesteps,
                num_episodes=num_episodes,
                explore=explore,
            )
        per = None
        per_eps = None
        if num_timesteps is not None:
            per = max(1, num_timesteps // self._manager.num_actors)
        if num_episodes is not None:
            per_eps = max(1, num_episodes // self._manager.num_actors)
        results = self._manager.foreach_actor(
            "sample", num_timesteps=per, num_episodes=per_eps, explore=explore
        )
        episodes = []
        for _, eps in results:
            episodes.extend(eps)
        return episodes

    def sync_weights(self, weights) -> None:
        """Broadcast learner weights to every runner via one object-store
        put (reference env_runner_group.py:522)."""
        if self._local is not None:
            self._local.set_weights(weights)
            return
        ref = ray_tpu.put(weights)
        self._manager.foreach_actor("set_weights", ref)

    def stats(self) -> List[Dict[str, Any]]:
        if self._local is not None:
            return [self._local.stats()]
        return [s for _, s in self._manager.foreach_actor("stats")]

    def get_metrics(self) -> Dict[str, Any]:
        """Drain completed-episode returns from every runner."""
        if self._local is not None:
            return self._local.get_metrics()
        returns: List[float] = []
        module_returns: Dict[str, List[float]] = {}
        for _, m in self._manager.foreach_actor("get_metrics"):
            returns.extend(m["episode_returns"])
            for mid, rs in m.get("module_returns", {}).items():
                module_returns.setdefault(mid, []).extend(rs)
        out: Dict[str, Any] = {"episode_returns": returns}
        if module_returns:
            out["module_returns"] = module_returns
        return out

    def stop(self):
        if self._manager:
            self._manager.shutdown()
