"""SingleAgentEpisode: the trajectory container.

Reference: rllib/env/single_agent_episode.py — append-only arrays of
observations/actions/rewards plus per-step extra model outputs (e.g.
action logp), finalized to numpy for transport between env runners and
learners.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np


class SingleAgentEpisode:
    def __init__(self, initial_observation=None):
        self.observations: List[Any] = (
            [] if initial_observation is None else [initial_observation]
        )
        self.actions: List[Any] = []
        self.rewards: List[float] = []
        self.extra_model_outputs: Dict[str, List[Any]] = {}
        self.is_terminated = False
        self.is_truncated = False
        self._finalized = False

    def add_env_step(
        self,
        observation,
        action,
        reward: float,
        *,
        terminated: bool = False,
        truncated: bool = False,
        extra_model_outputs: Optional[Dict[str, Any]] = None,
    ) -> None:
        assert not self._finalized
        self.observations.append(observation)
        self.actions.append(action)
        self.rewards.append(float(reward))
        self.is_terminated = terminated
        self.is_truncated = truncated
        for k, v in (extra_model_outputs or {}).items():
            self.extra_model_outputs.setdefault(k, []).append(v)

    def __len__(self) -> int:
        return len(self.actions)

    @property
    def is_done(self) -> bool:
        return self.is_terminated or self.is_truncated

    def get_return(self) -> float:
        return float(sum(self.rewards))

    def finalize(self) -> "SingleAgentEpisode":
        """Convert python lists to stacked numpy arrays for transport."""
        if not self._finalized:
            self.observations = np.stack([np.asarray(o) for o in self.observations])
            self.actions = np.asarray(self.actions)
            self.rewards = np.asarray(self.rewards, dtype=np.float32)
            self.extra_model_outputs = {
                k: np.asarray(v) for k, v in self.extra_model_outputs.items()
            }
            self._finalized = True
        return self

    def cut(self) -> "SingleAgentEpisode":
        """Continue an unfinished episode in a fresh chunk starting from
        the last observation (reference: episode.cut for truncation at
        sample boundaries)."""
        chunk = SingleAgentEpisode(initial_observation=self.observations[-1])
        return chunk
