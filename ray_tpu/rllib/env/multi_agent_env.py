"""MultiAgentEnv: the N-agents-one-environment API.

Reference: rllib/env/multi_agent_env.py — reset/step speak per-agent
dicts keyed by agent id; termination dicts carry the special "__all__"
key ending the whole episode; agents may appear/disappear between
steps. ``make_multi_agent`` wraps a single-agent gym env into N
independent copies sharing one step clock (reference
multi_agent_env.py:414 make_multi_agent).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Set, Tuple, Union


class MultiAgentEnv:
    """Subclass and implement reset() and step().

    - ``possible_agents``: all agent ids that may ever appear.
    - ``observation_space(agent_id)`` / ``action_space(agent_id)``.
    - ``reset() -> (obs_dict, info_dict)``
    - ``step(action_dict) -> (obs, rewards, terminateds, truncateds,
      infos)`` — all per-agent dicts; terminateds/truncateds also carry
      "__all__".
    """

    possible_agents: Tuple[str, ...] = ()

    def observation_space(self, agent_id: str):
        raise NotImplementedError

    def action_space(self, agent_id: str):
        raise NotImplementedError

    def reset(
        self, *, seed: Optional[int] = None
    ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        raise NotImplementedError

    def step(
        self, action_dict: Dict[str, Any]
    ) -> Tuple[
        Dict[str, Any],
        Dict[str, float],
        Dict[str, bool],
        Dict[str, bool],
        Dict[str, Any],
    ]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class _IndependentCopies(MultiAgentEnv):
    """N copies of a single-agent env behind the multi-agent API; copy i
    is agent ``agent_{i}``. Episodes end when every copy is done."""

    def __init__(self, env_maker: Callable[[], Any], num_agents: int):
        self._envs = {f"agent_{i}": env_maker() for i in range(num_agents)}
        self.possible_agents = tuple(self._envs)
        self._done: Set[str] = set()

    def observation_space(self, agent_id: str):
        return self._envs[agent_id].observation_space

    def action_space(self, agent_id: str):
        return self._envs[agent_id].action_space

    def reset(self, *, seed=None):
        self._done = set()
        obs, infos = {}, {}
        for i, (aid, env) in enumerate(self._envs.items()):
            o, info = env.reset(seed=None if seed is None else seed + i)
            obs[aid], infos[aid] = o, info
        return obs, infos

    def step(self, action_dict):
        obs, rewards, terms, truncs, infos = {}, {}, {}, {}, {}
        for aid, action in action_dict.items():
            if aid in self._done:
                continue
            o, r, te, tr, info = self._envs[aid].step(action)
            obs[aid], rewards[aid] = o, float(r)
            terms[aid], truncs[aid] = bool(te), bool(tr)
            infos[aid] = info
            if te or tr:
                self._done.add(aid)
        terms["__all__"] = len(self._done) == len(self._envs)
        truncs["__all__"] = False
        return obs, rewards, terms, truncs, infos

    def close(self):
        for env in self._envs.values():
            env.close()


def agent_id_mapping(agent_id: str) -> str:
    """Picklable default policy_mapping_fn: one module per agent id."""
    return agent_id


class ConstantMapping:
    """Picklable mapping sending every agent to one shared module."""

    def __init__(self, module_id: str):
        self.module_id = module_id

    def __call__(self, agent_id: str) -> str:
        return self.module_id


class _MultiAgentMaker:
    """Picklable env-maker returned by make_multi_agent (closures can't
    ship to remote env-runner actors)."""

    def __init__(self, env_spec: Union[str, Callable], num_agents: int):
        self.env_spec = env_spec
        self.num_agents = num_agents

    def __call__(
        self, env_config: Optional[Dict[str, Any]] = None
    ) -> MultiAgentEnv:
        import functools

        import gymnasium as gym

        cfg = dict(env_config or {})
        n = int(cfg.pop("num_agents", self.num_agents))
        if callable(self.env_spec):
            return _IndependentCopies(
                functools.partial(self.env_spec, cfg), n
            )
        return _IndependentCopies(
            functools.partial(gym.make, self.env_spec, **cfg), n
        )


def make_multi_agent(
    env_spec: Union[str, Callable], num_agents: int = 2
) -> Callable[[Dict[str, Any]], MultiAgentEnv]:
    """Factory: multi-agent wrapper of ``num_agents`` independent
    copies of a gym env id or env-maker callable."""
    return _MultiAgentMaker(env_spec, num_agents)
