"""FaultTolerantActorManager: keep a fleet of actors useful through
failures.

Reference: rllib/utils/actor_manager.py:196 — calls fan out to healthy
actors; an actor that raises a system error is marked unhealthy and
restarted (here: re-created from its factory), and results from the
dead actor are dropped rather than failing the caller.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu.exceptions import RayActorError, WorkerCrashedError


class FaultTolerantActorManager:
    def __init__(self, actor_factory: Callable[[int], Any], num_actors: int):
        self._factory = actor_factory
        self._actors: Dict[int, Any] = {
            i: actor_factory(i) for i in range(num_actors)
        }
        self._restarts = 0

    @property
    def num_actors(self) -> int:
        return len(self._actors)

    @property
    def num_restarts(self) -> int:
        return self._restarts

    def healthy_actor_ids(self) -> List[int]:
        return sorted(self._actors)

    def actor(self, i: int):
        return self._actors[i]

    def foreach_actor(
        self,
        fn_name: str,
        *args,
        kwargs_per_actor: Optional[Dict[int, dict]] = None,
        # Liveness bound, not a perf assertion: a restarted actor pays a
        # fresh jax compile, which on a contended host can take minutes.
        timeout: Optional[float] = 600.0,
        **kwargs,
    ) -> List[Tuple[int, Any]]:
        """Call ``actor.<fn_name>(*args)`` on every actor; returns
        [(actor_id, result)] for the calls that succeeded, restarting
        actors that died."""
        refs = {}
        for i, actor in self._actors.items():
            kw = dict(kwargs)
            kw.update((kwargs_per_actor or {}).get(i, {}))
            refs[i] = getattr(actor, fn_name).remote(*args, **kw)
        results = []
        for i, ref in refs.items():
            try:
                results.append((i, ray_tpu.get(ref, timeout=timeout)))
            except (RayActorError, WorkerCrashedError):
                self._restart(i)
            except Exception:
                raise
        return results

    def _restart(self, i: int):
        self._restarts += 1
        try:
            ray_tpu.kill(self._actors[i])
        except Exception:  # noqa: BLE001
            pass
        self._actors[i] = self._factory(i)

    def shutdown(self):
        for actor in self._actors.values():
            try:
                ray_tpu.kill(actor)
            except Exception:  # noqa: BLE001
                pass
        self._actors.clear()
