"""Replay buffers for off-policy algorithms.

Reference: rllib/utils/replay_buffers/ — uniform ring buffer and
proportional prioritized replay (PER, sum-tree). Stored as flat numpy
column arrays so sampling produces a ready train batch with zero copies
beyond fancy-indexing.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np


class ReplayBuffer:
    """Uniform FIFO transition buffer."""

    def __init__(self, capacity: int = 100_000, seed: Optional[int] = None):
        self.capacity = capacity
        self._cols: Dict[str, np.ndarray] = {}
        self._next = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add_batch(self, batch: Dict[str, np.ndarray]) -> None:
        n = len(next(iter(batch.values())))
        if not self._cols:
            for k, v in batch.items():
                v = np.asarray(v)
                self._cols[k] = np.zeros(
                    (self.capacity,) + v.shape[1:], v.dtype
                )
        for i in range(n):
            j = self._next
            for k, v in batch.items():
                self._cols[k][j] = v[i]
            self._on_add(j)
            self._next = (self._next + 1) % self.capacity
            self._size = min(self._size + 1, self.capacity)

    def _on_add(self, idx: int) -> None:
        pass

    def add_episodes(self, episodes) -> None:
        from ..connectors.connector_v2 import EpisodesToBatch

        self.add_batch(EpisodesToBatch()(episodes=episodes))

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self._size, size=batch_size)
        out = {k: v[idx] for k, v in self._cols.items()}
        out["batch_indexes"] = idx
        return out

    def update_priorities(self, idx, priorities) -> None:
        pass


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional PER with a binary sum-tree (reference:
    prioritized_episode_buffer / segment trees)."""

    def __init__(
        self,
        capacity: int = 100_000,
        alpha: float = 0.6,
        beta: float = 0.4,
        eps: float = 1e-6,
        seed: Optional[int] = None,
    ):
        super().__init__(capacity, seed)
        self.alpha = alpha
        self.beta = beta
        self.eps = eps
        size = 1
        while size < capacity:
            size *= 2
        self._tree_size = size
        self._tree = np.zeros(2 * size, np.float64)
        self._max_priority = 1.0

    def _set_priority(self, idx: int, priority: float) -> None:
        i = idx + self._tree_size
        delta = priority - self._tree[i]
        while i >= 1:
            self._tree[i] += delta
            i //= 2

    def _on_add(self, idx: int) -> None:
        self._set_priority(idx, self._max_priority**self.alpha)

    def _sample_idx(self, mass: float) -> int:
        i = 1
        while i < self._tree_size:
            left = 2 * i
            if self._tree[left] >= mass:
                i = left
            else:
                mass -= self._tree[left]
                i = left + 1
        return i - self._tree_size

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        total = self._tree[1]
        masses = self._rng.random(batch_size) * total
        idx = np.array([self._sample_idx(m) for m in masses], np.int64)
        idx = np.clip(idx, 0, self._size - 1)
        probs = np.array(
            [self._tree[i + self._tree_size] / total for i in idx], np.float64
        )
        weights = (self._size * np.maximum(probs, 1e-12)) ** (-self.beta)
        weights = weights / weights.max()
        out = {k: v[idx] for k, v in self._cols.items()}
        out["batch_indexes"] = idx
        out["weights"] = weights.astype(np.float32)
        return out

    def update_priorities(self, idx, priorities) -> None:
        for i, p in zip(np.asarray(idx), np.asarray(priorities)):
            p = float(abs(p)) + self.eps
            self._max_priority = max(self._max_priority, p)
            self._set_priority(int(i), p**self.alpha)
