"""Algorithm: the trainable facade over env runners + learners.

Reference: rllib/algorithms/algorithm.py (step :802, default
training_step :1576). Subclasses implement ``training_step``;
``train()`` (from the Tune Trainable API) wraps it with metric
aggregation, so every algorithm is directly tunable with
ray_tpu.tune.Tuner.
"""
from __future__ import annotations

import os
import pickle
import time
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

from ...tune.trainable import Trainable
from ..core.learner_group import LearnerGroup
from ..env.env_runner_group import EnvRunnerGroup


class Algorithm(Trainable):
    learner_class: Optional[type] = None

    def __init__(self, config=None, **kwargs):
        # Tune passes a dict; direct use passes an AlgorithmConfig.
        from .algorithm_config import AlgorithmConfig

        if isinstance(config, dict):
            cfg_obj = config.get("__algorithm_config__")
            if cfg_obj is None:
                raise ValueError(
                    "Pass an AlgorithmConfig (or a dict containing "
                    "'__algorithm_config__')"
                )
            config = cfg_obj
        assert isinstance(config, AlgorithmConfig)
        self._iteration = 0
        self._total_env_steps = 0
        self._episode_returns: deque = deque(maxlen=100)
        self._module_returns: Dict[str, deque] = {}
        self._start = time.monotonic()
        # Trainable.__init__ assigns self.config = the dict and calls
        # setup(); setup() re-binds self.config to the AlgorithmConfig.
        super().__init__(config={"__algorithm_config__": config})

    # ----------------------------------------------------------- setup
    def setup(self, config_dict) -> None:
        import gymnasium as gym

        self.config = config_dict["__algorithm_config__"].copy()
        # Tune-sampled hyperparams arrive as extra keys in the trial
        # config dict; apply them as overrides (lr, gamma, ...).
        for k, v in config_dict.items():
            if k != "__algorithm_config__" and hasattr(self.config, k):
                setattr(self.config, k, v)
        probe = (
            self.config.env(self.config.env_config)
            if callable(self.config.env)
            else gym.make(self.config.env, **(self.config.env_config or {}))
        )
        if self.config.is_multi_agent:
            from ..core.multi_agent_learner_group import (
                MultiAgentLearnerGroup,
            )

            self._module_spec = self.config.multi_module_spec(probe)
            group_cls = MultiAgentLearnerGroup
        else:
            self._module_spec = self.config.module_spec(
                probe.observation_space, probe.action_space
            )
            group_cls = LearnerGroup
        probe.close()
        self.env_runner_group = EnvRunnerGroup(self.env_runner_config())
        self.learner_group = group_cls(
            learner_cls=self.learner_class,
            module_spec=self._module_spec,
            config=self.learner_config(),
        )
        # Push initial learner weights so runners and learner agree.
        self.env_runner_group.sync_weights(self.learner_group.get_weights())

    def learner_config(self) -> Dict[str, Any]:
        return self.config.learner_config()  # subclass extras live on Config

    def env_runner_config(self) -> Dict[str, Any]:
        """Hook: algorithms may add connectors (e.g. DQN's ε-greedy)."""
        return self.config.env_runner_config(self._module_spec)

    # ------------------------------------------------------------ train
    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def step(self) -> Dict[str, Any]:
        learner_metrics = self.training_step()
        runner_metrics = self.env_runner_group.get_metrics()
        self._episode_returns.extend(runner_metrics["episode_returns"])
        for mid, rets in runner_metrics.get("module_returns", {}).items():
            self._module_returns.setdefault(
                mid, deque(maxlen=100)
            ).extend(rets)
        self._iteration += 1
        result = {
            "training_iteration": self._iteration,
            "num_env_steps_sampled_lifetime": self._total_env_steps,
            "time_total_s": time.monotonic() - self._start,
            "env_runners": {
                "episode_return_mean": (
                    float(np.mean(self._episode_returns))
                    if self._episode_returns
                    else float("nan")
                ),
                "num_episodes": len(self._episode_returns),
                "num_healthy_workers": (
                    self.env_runner_group.num_healthy_env_runners
                ),
                "num_restarts": self.env_runner_group.num_restarts,
            },
            "learners": learner_metrics,
        }
        if self._module_returns:
            result["env_runners"]["module_episode_return_mean"] = {
                mid: float(np.mean(rets))
                for mid, rets in self._module_returns.items()
                if rets
            }
        # Flat aliases used by Tune stoppers/schedulers.
        result["episode_return_mean"] = result["env_runners"][
            "episode_return_mean"
        ]
        return result

    def train(self) -> Dict[str, Any]:
        return self.step()

    def _record_episodes(self, episodes: List) -> None:
        # Returns are tracked in the runners (chunks spanning sample
        # boundaries must accumulate); here only step accounting.
        for ep in episodes:
            self._total_env_steps += len(ep)

    # ------------------------------------------------------- checkpoint
    def save_checkpoint(self, checkpoint_dir: str) -> str:
        state = {
            "learner": self.learner_group.get_state(),
            "iteration": self._iteration,
            "total_env_steps": self._total_env_steps,
        }
        path = os.path.join(checkpoint_dir, "algorithm_state.pkl")
        with open(path, "wb") as f:
            pickle.dump(state, f)
        return checkpoint_dir

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        with open(
            os.path.join(checkpoint_dir, "algorithm_state.pkl"), "rb"
        ) as f:
            state = pickle.load(f)
        self.learner_group.set_state(state["learner"])
        self._iteration = state["iteration"]
        self._total_env_steps = state["total_env_steps"]
        self.env_runner_group.sync_weights(self.learner_group.get_weights())

    save = save_checkpoint
    restore = load_checkpoint

    def stop(self) -> None:
        self.env_runner_group.stop()
        self.learner_group.shutdown()

    cleanup = stop
