"""MARWIL + BC: offline RL from recorded experience.

Reference: rllib/algorithms/marwil/ (exponentially advantage-weighted
imitation, Wang et al. 2018) and rllib/algorithms/bc/ (BC = MARWIL with
beta=0, marwil.py:35). Training consumes a recorded sample dataset (see
rllib.offline) instead of env runners; the env is only probed for
spaces and used for explore=False evaluation rollouts.

Loss (marwil_torch_learner.py): vf trains toward the monte-carlo
return-to-go; the policy maximizes exp(beta * A / c) - weighted logp,
where c^2 is a running average of squared advantages (the paper's
normalizer) maintained outside the jitted program like APPO's adaptive
KL coefficient.
"""
from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np

from ..core.learner import Learner
from ..core.rl_module import Columns
from .algorithm import Algorithm
from .algorithm_config import AlgorithmConfig


class MARWILConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.beta = 1.0
        self.vf_coeff = 1.0
        self.grad_clip = 40.0
        self.lr = 1e-3
        self.train_batch_size = 2000
        self.input_: Any = None  # sample dir / file list (rllib "input")
        self.moving_average_sqd_adv_norm_update_rate = 1e-4

    @property
    def algo_class(self):
        return MARWIL

    def offline_data(self, *, input_=None) -> "MARWILConfig":
        if input_ is not None:
            self.input_ = input_
        return self

    def learner_config(self):
        cfg = super().learner_config()
        cfg.update(
            beta=self.beta,
            vf_coeff=self.vf_coeff,
            gamma=self.gamma,
            c_update_rate=self.moving_average_sqd_adv_norm_update_rate,
        )
        return cfg


class BCConfig(MARWILConfig):
    """Behavior cloning: pure -logp imitation (reference:
    rllib/algorithms/bc/bc.py — MARWIL with beta=0, no value head in
    the loss)."""

    def __init__(self):
        super().__init__()
        self.beta = 0.0
        self.vf_coeff = 0.0

    @property
    def algo_class(self):
        return BC


class MARWILLearner(Learner):
    def build(self):
        super().build()
        # c^2: running estimate of E[A^2] (paper's advantage normalizer).
        self._ma_sqd_adv = 100.0

    def build_batch(self, episodes) -> Dict[str, np.ndarray]:
        from ..connectors.connector_v2 import EpisodesToBatch

        batch = EpisodesToBatch()(episodes=episodes)
        gamma = self.config["gamma"]
        returns = []
        for ep in episodes:
            r = np.asarray(ep.rewards, np.float32)
            out = np.zeros_like(r)
            acc = 0.0
            for t in range(len(r) - 1, -1, -1):
                acc = r[t] + gamma * acc
                out[t] = acc
            returns.append(out)
        batch[Columns.VALUE_TARGETS] = np.concatenate(returns)
        return batch

    def compute_loss(self, params, batch, rng) -> Tuple[Any, Dict[str, Any]]:
        import jax
        import jax.numpy as jnp

        cfg = self.config
        out = self.module.forward_train(params, batch)
        logits = out[Columns.ACTION_DIST_INPUTS]
        z = logits - jax.scipy.special.logsumexp(logits, axis=-1, keepdims=True)
        actions = batch[Columns.ACTIONS].astype(jnp.int32)
        logp = jnp.take_along_axis(z, actions[:, None], axis=-1)[:, 0]

        if cfg["beta"] > 0.0:
            vf = out[Columns.VF_PREDS]
            adv = jax.lax.stop_gradient(
                batch[Columns.VALUE_TARGETS] - vf
            )
            weight = jnp.exp(
                jnp.clip(
                    cfg["beta"] * adv * batch["marwil_c_inv"], -20.0, 2.0
                )
            )
            policy_loss = -jnp.mean(weight * logp)
            vf_loss = jnp.mean(
                jnp.square(vf - batch[Columns.VALUE_TARGETS])
            )
            total = policy_loss + cfg["vf_coeff"] * vf_loss
            metrics = {
                "policy_loss": policy_loss,
                "vf_loss": vf_loss,
                "mean_advantage": jnp.mean(adv),
                "mean_sqd_advantage": jnp.mean(jnp.square(adv)),
            }
        else:
            policy_loss = -jnp.mean(logp)
            total = policy_loss
            metrics = {
                "policy_loss": policy_loss,
                "mean_sqd_advantage": jnp.zeros(()),
            }
        metrics["logp_mean"] = jnp.mean(logp)
        return total, metrics

    def update(self, batch):
        import jax.numpy as jnp

        if self.config["beta"] > 0.0:
            batch = dict(
                batch,
                marwil_c_inv=jnp.asarray(
                    1.0 / float(np.sqrt(self._ma_sqd_adv) + 1e-8),
                    jnp.float32,
                ),
            )
        metrics = super().update(batch)
        if self.config["beta"] > 0.0:
            rate = self.config.get("c_update_rate", 1e-4)
            self._ma_sqd_adv += rate * (
                metrics["mean_sqd_advantage"] - self._ma_sqd_adv
            )
            metrics["sqd_adv_norm"] = self._ma_sqd_adv
        return metrics


class MARWIL(Algorithm):
    learner_class = MARWILLearner

    def setup(self, config_dict) -> None:
        super().setup(config_dict)
        if not self.config.input_:
            raise ValueError(
                "MARWIL/BC are offline algorithms: set "
                "config.offline_data(input_=<sample dir>)"
            )
        from ..offline import SampleReader

        self._reader = SampleReader(self.config.input_, seed=self.config.seed)
        self._batch_iter = self._reader.iter_episodes(
            self.config.train_batch_size
        )

    def training_step(self) -> Dict[str, Any]:
        episodes = next(self._batch_iter)
        self._record_episodes(episodes)
        return self.learner_group.update_from_episodes(episodes)

    def evaluate(self, num_episodes: int = 10) -> Dict[str, Any]:
        """Policy rollouts with the current learned weights (reference:
        Algorithm.evaluate)."""
        self.env_runner_group.sync_weights(self.learner_group.get_weights())
        episodes = self.env_runner_group.sample(
            num_episodes=num_episodes, explore=False
        )
        returns = [float(np.sum(ep.rewards)) for ep in episodes]
        return {
            "episode_return_mean": float(np.mean(returns)),
            "num_episodes": len(returns),
        }


class BC(MARWIL):
    pass
