"""SAC: soft actor-critic for continuous control.

Reference: rllib/algorithms/sac/ — off-policy maximum-entropy RL with a
squashed-Gaussian policy, twin Q networks with a polyak-averaged target
pair, and automatic entropy-temperature tuning. The TPU-first inversion
of the reference's three torch optimizers: actor, twin-critic, and
log-alpha losses are combined into ONE jitted update with
stop-gradients partitioning the flows (adam is per-leaf, so a combined
loss whose gradients only touch each component's leaves is exactly
equivalent to separate optimizers), and the polyak target update is a
second tiny jitted program — the whole SGD step never leaves the
device.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..connectors.connector_v2 import ConnectorPipelineV2, ConnectorV2
from ..core.learner import Learner
from ..core.rl_module import Columns, RLModule, _mlp
from ..utils.replay_buffers import PrioritizedReplayBuffer, ReplayBuffer
from .algorithm import Algorithm
from .algorithm_config import AlgorithmConfig

LOG_STD_MIN, LOG_STD_MAX = -20.0, 2.0


class SACModule(RLModule):
    """Squashed-Gaussian actor + twin Q critics over MLP trunks
    (reference: rllib/algorithms/sac/sac_catalog.py — pi outputs
    [mean, log_std]; Q heads consume concat(obs, action))."""

    def setup(self) -> None:
        hidden = tuple(self.model_config.get("fcnet_hiddens", (256, 256)))
        act_dim = self.num_actions()
        self._pi = _mlp(hidden, 2 * act_dim, out_scale=0.01)
        self._q1 = _mlp(hidden, 1, out_scale=1.0)
        self._q2 = _mlp(hidden, 1, out_scale=1.0)
        low = np.asarray(self.action_space.low, np.float32)
        high = np.asarray(self.action_space.high, np.float32)
        self.action_scale = (high - low) / 2.0
        self.action_center = (high + low) / 2.0

    def init_params(self, rng):
        import jax
        import jax.numpy as jnp

        obs = jnp.zeros((1, self.input_dim()), jnp.float32)
        oa = jnp.zeros((1, self.input_dim() + self.num_actions()), jnp.float32)
        k1, k2, k3 = jax.random.split(rng, 3)
        return {
            "pi": self._pi.init(k1, obs),
            "q1": self._q1.init(k2, oa),
            "q2": self._q2.init(k3, oa),
            "log_alpha": jnp.zeros((), jnp.float32),
        }

    # --------------------------------------------------------- forwards
    def forward_exploration(self, params, batch):
        dist = self._pi.apply(params["pi"], batch[Columns.OBS])
        return {Columns.ACTION_DIST_INPUTS: dist}

    def forward_train(self, params, batch):
        return self.forward_exploration(params, batch)

    def q_values(self, params, obs, actions):
        """Both critics on (s, a); actions are env-scale."""
        import jax.numpy as jnp

        oa = jnp.concatenate(
            [obs.reshape(obs.shape[0], -1), actions], axis=-1
        )
        return (
            self._q1.apply(params["q1"], oa)[..., 0],
            self._q2.apply(params["q2"], oa)[..., 0],
        )

    def sample_action(self, params, obs, rng):
        """Reparameterized tanh-Gaussian sample → (env_action, logp)."""
        import jax
        import jax.numpy as jnp

        dist = self._pi.apply(params["pi"], obs)
        mean, log_std = jnp.split(dist, 2, axis=-1)
        log_std = jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)
        std = jnp.exp(log_std)
        u = mean + std * jax.random.normal(rng, mean.shape)
        logp_u = jnp.sum(
            -0.5 * jnp.square((u - mean) / std)
            - log_std
            - 0.5 * jnp.log(2.0 * jnp.pi),
            axis=-1,
        )
        t = jnp.tanh(u)
        # Change of variables for the tanh squash + affine scale
        # (SAC paper appendix C).
        logp = logp_u - jnp.sum(
            jnp.log(self.action_scale * (1.0 - jnp.square(t)) + 1e-6),
            axis=-1,
        )
        return t * self.action_scale + self.action_center, logp


class SampleSquashedGaussianActions(ConnectorV2):
    """module-to-env: sample env-scale actions from [mean, log_std]
    dist inputs (exploration) or pass the squashed mean (inference)."""

    def __init__(self, action_scale, action_center, explore: bool = True,
                 rng: Optional[np.random.Generator] = None):
        self.action_scale = np.asarray(action_scale, np.float32)
        self.action_center = np.asarray(action_center, np.float32)
        self.explore = explore
        self.rng = rng or np.random.default_rng()

    def __call__(self, *, rl_module=None, batch=None, episodes=None, **kwargs):
        dist = np.asarray(batch[Columns.ACTION_DIST_INPUTS], np.float32)
        mean, log_std = np.split(dist, 2, axis=-1)
        if kwargs.get("explore", self.explore):
            std = np.exp(np.clip(log_std, LOG_STD_MIN, LOG_STD_MAX))
            u = mean + std * self.rng.standard_normal(mean.shape).astype(
                np.float32
            )
        else:
            u = mean
        batch["actions"] = (
            np.tanh(u) * self.action_scale + self.action_center
        )
        return batch


class SACConfig(AlgorithmConfig):
    default_module_class = SACModule

    def __init__(self):
        super().__init__()
        self.lr = 3e-4
        self.gamma = 0.99
        self.train_batch_size = 256
        self.tau = 0.005
        self.initial_alpha = 1.0
        self.target_entropy: Optional[float] = None  # None → -act_dim
        self.replay_buffer_capacity = 100_000
        self.prioritized_replay = False
        self.per_alpha = 0.6
        self.per_beta = 0.4
        self.num_steps_sampled_before_learning_starts = 1000
        self.sample_timesteps_per_iteration = 500
        self.updates_per_iteration = 250
        self.model_config = {"fcnet_hiddens": (256, 256)}

    @property
    def algo_class(self):
        return SAC

    def learner_config(self):
        cfg = super().learner_config()
        cfg.update(
            gamma=self.gamma,
            tau=self.tau,
            initial_alpha=self.initial_alpha,
            target_entropy=self.target_entropy,
            minibatch_size=None,
            num_epochs=1,
        )
        return cfg


class SACLearner(Learner):
    def build(self):
        super().build()
        import jax
        import jax.numpy as jnp

        self.params["log_alpha"] = jnp.asarray(
            float(np.log(self.config.get("initial_alpha", 1.0))), jnp.float32
        )
        self.opt_state = self._tx.init(self.params)
        # Target critics start as copies of the online pair.
        self.target_q = {
            "q1": jax.device_get(self.params["q1"]),
            "q2": jax.device_get(self.params["q2"]),
        }
        te = self.config.get("target_entropy")
        self._target_entropy = (
            float(te) if te is not None else -float(self.module.num_actions())
        )
        tau = float(self.config["tau"])

        @jax.jit
        def polyak(target, online):
            return jax.tree_util.tree_map(
                lambda t, o: (1.0 - tau) * t + tau * o, target, online
            )

        self._polyak = polyak

    def build_batch(self, episodes):
        from ..connectors.connector_v2 import EpisodesToBatch

        return EpisodesToBatch()(episodes=episodes)

    def compute_loss(self, params, batch, rng) -> Tuple[Any, Dict[str, Any]]:
        import jax
        import jax.numpy as jnp

        cfg = self.config
        stop = jax.lax.stop_gradient
        obs = batch[Columns.OBS]
        next_obs = batch[Columns.NEXT_OBS]
        actions = batch[Columns.ACTIONS]
        if actions.ndim == 1:
            actions = actions[:, None]
        rng_next, rng_pi = jax.random.split(rng)
        alpha = jnp.exp(params["log_alpha"])

        # ---- critic loss: entropy-regularized Bellman target from the
        # polyak target pair (riding in the batch like DQN's target).
        a_next, logp_next = self.module.sample_action(
            params, next_obs, rng_next
        )
        tq1, tq2 = self.module.q_values(
            batch["target_q"], next_obs, a_next
        )
        target = stop(
            batch[Columns.REWARDS]
            + cfg["gamma"]
            * (1.0 - batch[Columns.TERMINATEDS])
            * (jnp.minimum(tq1, tq2) - stop(alpha) * logp_next)
        )
        q1, q2 = self.module.q_values(params, obs, actions)
        weights = batch.get("weights", 1.0)
        critic_loss = jnp.mean(
            weights * (jnp.square(q1 - target) + jnp.square(q2 - target))
        )

        # ---- actor loss: reparameterized sample through FROZEN critics
        # (gradient flows to the action, not the Q weights).
        a_pi, logp_pi = self.module.sample_action(params, obs, rng_pi)
        frozen = {"q1": stop(params["q1"]), "q2": stop(params["q2"])}
        fq1, fq2 = self.module.q_values(frozen, obs, a_pi)
        actor_loss = jnp.mean(
            stop(alpha) * logp_pi - jnp.minimum(fq1, fq2)
        )

        # ---- temperature: drive policy entropy toward the target.
        alpha_loss = -jnp.mean(
            params["log_alpha"] * stop(logp_pi + self._target_entropy)
        )

        total = critic_loss + actor_loss + alpha_loss
        return total, {
            "critic_loss": critic_loss,
            "actor_loss": actor_loss,
            "alpha": alpha,
            "entropy": -jnp.mean(logp_pi),
            "qf_mean": jnp.mean(q1),
        }

    def update(self, batch):
        batch = dict(batch, target_q=self.target_q)
        metrics = super().update(batch)
        self.target_q = self._polyak(
            self.target_q, {"q1": self.params["q1"], "q2": self.params["q2"]}
        )
        return metrics

    def td_errors(self, batch) -> np.ndarray:
        import jax
        import jax.numpy as jnp

        if not hasattr(self, "_td_jit"):

            def f(params, target_q, batch, rng):
                obs = batch[Columns.OBS]
                actions = batch[Columns.ACTIONS]
                if actions.ndim == 1:
                    actions = actions[:, None]
                a_next, logp_next = self.module.sample_action(
                    params, batch[Columns.NEXT_OBS], rng
                )
                tq1, tq2 = self.module.q_values(
                    target_q, batch[Columns.NEXT_OBS], a_next
                )
                alpha = jnp.exp(params["log_alpha"])
                target = (
                    batch[Columns.REWARDS]
                    + self.config["gamma"]
                    * (1.0 - batch[Columns.TERMINATEDS])
                    * (jnp.minimum(tq1, tq2) - alpha * logp_next)
                )
                q1, _ = self.module.q_values(params, obs, actions)
                return jnp.abs(q1 - target)

            self._td_jit = jax.jit(f)
        self._rng, rng = jax.random.split(self._rng)
        return np.asarray(
            jax.device_get(
                self._td_jit(self.params, self.target_q, batch, rng)
            )
        )

    def get_state(self) -> Dict[str, Any]:
        import jax

        state = super().get_state()
        state["target_q"] = jax.device_get(self.target_q)
        return state

    def set_state(self, state: Dict[str, Any]) -> None:
        super().set_state(state)
        if "target_q" in state:
            self.target_q = state["target_q"]


class SAC(Algorithm):
    learner_class = SACLearner

    def setup(self, config_dict) -> None:
        super().setup(config_dict)
        cfg = self.config
        if cfg.prioritized_replay:
            self.replay = PrioritizedReplayBuffer(
                cfg.replay_buffer_capacity,
                alpha=cfg.per_alpha,
                beta=cfg.per_beta,
                seed=cfg.seed,
            )
        else:
            self.replay = ReplayBuffer(
                cfg.replay_buffer_capacity, seed=cfg.seed
            )

    def env_runner_config(self) -> Dict[str, Any]:
        runner_cfg = super().env_runner_config()
        spec = self._module_spec
        low = np.asarray(spec.action_space.low, np.float32)
        high = np.asarray(spec.action_space.high, np.float32)
        runner_cfg["module_to_env"] = ConnectorPipelineV2(
            [
                SampleSquashedGaussianActions(
                    (high - low) / 2.0,
                    (high + low) / 2.0,
                    rng=np.random.default_rng(self.config.seed),
                )
            ]
        )
        return runner_cfg

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        episodes = self.env_runner_group.sample(
            num_timesteps=cfg.sample_timesteps_per_iteration
        )
        self._record_episodes(episodes)
        self.replay.add_episodes(episodes)
        if len(self.replay) < cfg.num_steps_sampled_before_learning_starts:
            return {"buffer_size": float(len(self.replay))}
        assert self.learner_group.is_local, (
            "SAC uses a local learner (replay lives with the algorithm)"
        )
        learner: SACLearner = self.learner_group._local
        metrics_list = []
        for _ in range(cfg.updates_per_iteration):
            batch = self.replay.sample(cfg.train_batch_size)
            idx = batch.pop("batch_indexes")
            m = learner.update(dict(batch))
            if cfg.prioritized_replay:
                self.replay.update_priorities(idx, learner.td_errors(batch))
            metrics_list.append(m)
        self.env_runner_group.sync_weights(self.learner_group.get_weights())
        out = {
            k: float(np.mean([m[k] for m in metrics_list]))
            for k in metrics_list[0]
        }
        out["buffer_size"] = float(len(self.replay))
        return out
