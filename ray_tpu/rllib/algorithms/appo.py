"""APPO: asynchronous PPO (IMPALA pipeline + clipped surrogate).

Reference: rllib/algorithms/appo/ — the IMPALA architecture (continuous
async sampling, V-trace off-policy correction) with PPO's clipped
surrogate objective replacing the plain policy gradient, plus an
optional KL penalty against a slow-moving target policy
(appo.py:88-104, appo_torch_learner.py). Learner update is one jitted
program; the target-policy refresh is a periodic host-side copy like
DQN's target sync.
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np

from .impala import IMPALA, IMPALAConfig, IMPALALearner


class APPOConfig(IMPALAConfig):
    def __init__(self):
        super().__init__()
        self.clip_param = 0.4
        self.use_kl_loss = False
        self.kl_coeff = 0.2
        self.kl_target = 0.01
        # In learner updates (reference counts target updates in env
        # steps; one update == one train batch here).
        self.target_network_update_freq = 2

    @property
    def algo_class(self):
        return APPO

    def learner_config(self):
        cfg = super().learner_config()
        cfg.update(
            clip_param=self.clip_param,
            use_kl_loss=self.use_kl_loss,
            kl_coeff=self.kl_coeff,
            kl_target=self.kl_target,
            target_network_update_freq=self.target_network_update_freq,
        )
        return cfg


class APPOLearner(IMPALALearner):
    """V-trace targets exactly as IMPALA; the policy term swaps the
    plain PG for PPO's clipped surrogate, with the ratio taken against
    the behavior policy's logp recorded at sample time."""

    def build(self):
        super().build()
        import jax

        self.target_params = jax.device_get(self.params)
        self._updates = 0
        # Adaptive KL coefficient lives outside the jitted loss (it
        # changes between updates, not within one).
        self._kl_coeff = float(self.config.get("kl_coeff", 0.2))

    def compute_loss(self, params, batch, rng) -> Tuple[Any, Dict[str, Any]]:
        import jax
        import jax.numpy as jnp

        cfg = self.config
        from ..core.rl_module import Columns

        B, T = batch["actions"].shape
        obs_flat = batch["obs"].reshape((B * T,) + batch["obs"].shape[2:])
        out = self.module.forward_train(params, {Columns.OBS: obs_flat})
        logits = out[Columns.ACTION_DIST_INPUTS].reshape(B, T, -1)
        values = out[Columns.VF_PREDS].reshape(B, T)
        bootstrap = self.module.compute_values(params, batch["bootstrap_obs"])

        z = logits - jax.scipy.special.logsumexp(logits, axis=-1, keepdims=True)
        actions = batch["actions"].astype(jnp.int32)
        target_logp = jnp.take_along_axis(z, actions[..., None], axis=-1)[..., 0]

        mask = batch["mask"]
        rho = jax.lax.stop_gradient(
            jnp.exp(target_logp - batch["action_logp"])
        )
        rho_clip = jnp.minimum(rho, cfg["vtrace_clip_rho_threshold"])
        c_clip = jnp.minimum(rho, cfg["vtrace_clip_c_threshold"])
        bootstrap = jax.lax.stop_gradient(bootstrap)
        discounts = cfg["gamma"] * (1.0 - batch["terminateds"]) * mask
        values_stop = jax.lax.stop_gradient(values)
        next_valid = jnp.concatenate(
            [mask[:, 1:], jnp.zeros_like(mask[:, :1])], axis=1
        )
        v_shift = jnp.concatenate(
            [values_stop[:, 1:], jnp.zeros_like(bootstrap)[:, None]], axis=1
        )
        v_tp1 = next_valid * v_shift + (1.0 - next_valid) * bootstrap[:, None]
        deltas = mask * rho_clip * (
            batch["rewards"] + discounts * v_tp1 - values_stop
        )

        def scan_fn(acc, xs):
            delta_t, disc_t, c_t = xs
            acc = delta_t + disc_t * c_t * acc
            return acc, acc

        _, acc = jax.lax.scan(
            scan_fn,
            jnp.zeros((B,), values.dtype),
            (deltas.T, discounts.T, c_clip.T),
            reverse=True,
        )
        vs = values_stop + acc.T
        vs_shift = jnp.concatenate(
            [vs[:, 1:], jnp.zeros_like(bootstrap)[:, None]], axis=1
        )
        vs_tp1 = next_valid * vs_shift + (1.0 - next_valid) * bootstrap[:, None]
        pg_adv = jax.lax.stop_gradient(
            rho_clip * (batch["rewards"] + discounts * vs_tp1 - values_stop)
        )

        denom = jnp.maximum(mask.sum(), 1.0)
        if cfg.get("standardize_advantages", True):
            adv_mean = jnp.sum(pg_adv * mask) / denom
            adv_var = jnp.sum(jnp.square(pg_adv - adv_mean) * mask) / denom
            pg_adv = (pg_adv - adv_mean) / jnp.maximum(jnp.sqrt(adv_var), 1e-4)

        # ---- PPO clip on the importance ratio (the APPO difference).
        ratio = jnp.exp(target_logp - batch["action_logp"])
        clipped = jnp.clip(
            ratio, 1.0 - cfg["clip_param"], 1.0 + cfg["clip_param"]
        )
        surrogate = jnp.minimum(ratio * pg_adv, clipped * pg_adv)
        policy_loss = -jnp.sum(surrogate * mask) / denom

        vf_loss = 0.5 * jnp.sum(jnp.square(vs - values) * mask) / denom
        entropy = -jnp.sum(jnp.exp(z) * z * mask[..., None]) / denom
        total = (
            policy_loss
            + cfg["vf_loss_coeff"] * vf_loss
            - cfg["entropy_coeff"] * entropy
        )
        metrics = {
            "policy_loss": policy_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
            "mean_rho": jnp.sum(rho * mask) / denom,
        }
        if cfg.get("use_kl_loss"):
            # KL(target || online) against the slow policy, averaged
            # over valid steps (reference: appo_torch_learner KL term).
            t_logits = self.module.forward_train(
                batch["appo_target_params"], {Columns.OBS: obs_flat}
            )[Columns.ACTION_DIST_INPUTS].reshape(B, T, -1)
            tz = t_logits - jax.scipy.special.logsumexp(
                t_logits, axis=-1, keepdims=True
            )
            kl = jnp.sum(jnp.exp(tz) * (tz - z), axis=-1)
            mean_kl = jnp.sum(kl * mask) / denom
            total = total + batch["appo_kl_coeff"] * mean_kl
            metrics["mean_kl"] = mean_kl
        return total, metrics

    def update(self, batch):
        import jax
        import jax.numpy as jnp

        if self.config.get("use_kl_loss"):
            batch = dict(
                batch,
                appo_target_params=self.target_params,
                appo_kl_coeff=jnp.asarray(self._kl_coeff, jnp.float32),
            )
        metrics = super().update(batch)
        self._updates += 1
        if self.config.get("use_kl_loss") and "mean_kl" in metrics:
            # Reference's adaptive KL: 1.5x band around the target.
            if metrics["mean_kl"] > 2.0 * self.config["kl_target"]:
                self._kl_coeff *= 1.5
            elif metrics["mean_kl"] < 0.5 * self.config["kl_target"]:
                self._kl_coeff *= 0.5
            metrics["kl_coeff"] = self._kl_coeff
        if self._updates % max(
            1, int(self.config.get("target_network_update_freq", 2))
        ) == 0:
            self.target_params = jax.device_get(self.params)
        return metrics


class APPO(IMPALA):
    learner_class = APPOLearner
