"""DreamerV3: world-model RL — learn in imagination.

Reference: rllib/algorithms/dreamerv3/ (tf world model + imagination
actor-critic). TPU-first re-design: the entire training step — RSSM
sequence rollout (lax.scan), all world-model heads, the imagined
actor-critic rollout (a second scan) and three optimizers' gradients —
compiles into ONE jitted XLA program via combined losses with
stop-gradient partitions and per-group learning rates
(optax.multi_transform); nothing leaves the device between the
posterior scan and the parameter update.

The v3 signatures are kept: categorical latents (groups x classes)
with 1% unimix and straight-through gradients, symlog regression for
observations, twohot symexp bins for reward and value, free-bits KL
with the 0.1 representation-loss weighting, lambda-returns on imagined
trajectories, percentile-EMA return normalization, and an
EMA-regularized slow critic.

Like the reference, DreamerV3 does not use the shared env-runner
machinery: acting is recurrent (the RSSM state threads through the
episode), so the algorithm owns its vectorized collection loop and a
sequence-replay buffer of whole episodes.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .algorithm import Algorithm
from .algorithm_config import AlgorithmConfig


def _symlog(x):
    import jax.numpy as jnp

    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def _symexp(x):
    import jax.numpy as jnp

    return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)


class _TwoHot:
    """Twohot encoding over symlog-spaced bins (reference:
    dreamerv3/utils/two_hot.py): scalars become a categorical CE
    target, killing reward/value scale sensitivity."""

    def __init__(self, n_bins: int = 41, low: float = -20.0,
                 high: float = 20.0):
        import jax.numpy as jnp

        self.bins = jnp.linspace(low, high, n_bins)
        self.n = n_bins

    def encode(self, x):
        import jax.numpy as jnp

        x = jnp.clip(_symlog(x), self.bins[0], self.bins[-1])
        idx = jnp.clip(
            jnp.searchsorted(self.bins, x, side="right") - 1, 0, self.n - 2
        )
        lo, hi = self.bins[idx], self.bins[idx + 1]
        w_hi = (x - lo) / (hi - lo)
        # Scatter via one_hot (vectorized, no advanced indexing).
        import jax

        oh_lo = jax.nn.one_hot(idx, self.n) * (1.0 - w_hi)[..., None]
        oh_hi = jax.nn.one_hot(idx + 1, self.n) * w_hi[..., None]
        return oh_lo + oh_hi

    def decode(self, logits):
        import jax
        import jax.numpy as jnp

        probs = jax.nn.softmax(logits, axis=-1)
        return _symexp(jnp.sum(probs * self.bins, axis=-1))


class DreamerV3Config(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        # World model (tiny-by-default: CI trains on CPU; scale via
        # model_config for real runs).
        self.model_config = {
            "deter": 256,
            "stoch_groups": 8,
            "stoch_classes": 8,
            "units": 256,
            "bins": 41,
        }
        self.lr = 1e-4  # world model
        self.actor_lr = 3e-5
        self.critic_lr = 3e-5
        self.grad_clip = 100.0
        self.gamma = 0.997
        self.gae_lambda = 0.95
        self.horizon = 15
        self.entropy_coef = 3e-4
        self.free_bits = 1.0
        self.rep_loss_scale = 0.1
        self.dyn_loss_scale = 0.5
        self.critic_ema_decay = 0.98
        self.critic_ema_reg = 1.0
        self.batch_size_B = 8
        self.batch_length_T = 32
        self.train_ratio = 64.0  # replayed steps per env step
        self.num_envs = 4
        self.sample_timesteps_per_iteration = 400
        self.num_steps_sampled_before_learning_starts = 1000
        self.replay_capacity_steps = 100_000

    @property
    def algo_class(self):
        return DreamerV3


class _EpisodeReplay:
    """Stores whole episodes; samples (B, T) subsequences with
    `is_first` flags (reference: dreamerv3 EpisodeReplayBuffer)."""

    def __init__(self, capacity_steps: int, seed=None):
        self.capacity = capacity_steps
        self._eps: List[Dict[str, np.ndarray]] = []
        self._steps = 0
        self._rng = np.random.default_rng(seed)

    def add(self, obs, actions, rewards, terminateds):
        ep = {
            "obs": np.asarray(obs, np.float32),  # T+1 observations
            "actions": np.asarray(actions, np.int64),  # T
            "rewards": np.asarray(rewards, np.float32),  # T
            "terminated": bool(terminateds),
        }
        self._eps.append(ep)
        self._steps += len(ep["actions"])
        while self._steps > self.capacity and len(self._eps) > 1:
            old = self._eps.pop(0)
            self._steps -= len(old["actions"])

    def __len__(self):
        return self._steps

    def sample(self, B: int, T: int) -> Dict[str, np.ndarray]:
        """Each row: T steps; crossing an episode start sets is_first.
        Short episodes are padded by wrapping into another episode
        (standard dreamer replay semantics: the RSSM resets at
        is_first, so stitching is sound)."""
        obs_dim = self._eps[0]["obs"].shape[-1]
        out = {
            "obs": np.zeros((B, T, obs_dim), np.float32),
            "actions": np.zeros((B, T), np.int64),
            "rewards": np.zeros((B, T), np.float32),
            "continues": np.ones((B, T), np.float32),
            "is_first": np.zeros((B, T), np.float32),
        }
        for b in range(B):
            t = 0
            while t < T:
                ep = self._eps[self._rng.integers(len(self._eps))]
                n = len(ep["actions"])
                start = int(self._rng.integers(n)) if t == 0 else 0
                take = min(T - t, n - start)
                sl = slice(start, start + take)
                out["obs"][b, t : t + take] = ep["obs"][:-1][sl]
                out["actions"][b, t : t + take] = ep["actions"][sl]
                out["rewards"][b, t : t + take] = ep["rewards"][sl]
                if ep["terminated"] and start + take == n:
                    out["continues"][b, t + take - 1] = 0.0
                out["is_first"][b, t] = 1.0 if t == 0 or start == 0 else 0.0
                t += take
        return out


class DreamerV3(Algorithm):
    """Owns collection (recurrent acting), replay, and the one-program
    learner update."""

    learner_class = None  # self-contained: no shared Learner machinery

    def setup(self, config_dict) -> None:
        import gymnasium as gym
        import jax

        # Same config unpacking as Algorithm.setup, WITHOUT the shared
        # env-runner/learner groups (recurrent acting owns its loop).
        self.config = config_dict["__algorithm_config__"].copy()
        for k, v in config_dict.items():
            if k != "__algorithm_config__" and hasattr(self.config, k):
                setattr(self.config, k, v)
        cfg = self.config
        self._rng_key = jax.random.PRNGKey(cfg.seed or 0)
        env_spec = cfg.env
        self._envs = [
            (env_spec() if callable(env_spec) else gym.make(env_spec))
            for _ in range(cfg.num_envs)
        ]
        obs_space = self._envs[0].observation_space
        act_space = self._envs[0].action_space
        self._obs_dim = int(np.prod(obs_space.shape))
        self._n_actions = int(act_space.n)
        self.replay = _EpisodeReplay(cfg.replay_capacity_steps, cfg.seed)
        self._build_nets()
        self._build_update()
        # Per-env recurrent state + open episode accumulators.
        self._reset_collection()
        self._total_env_steps = 0
        self._updates = 0
        self._ep_returns: List[float] = []
        self._np_rng = np.random.default_rng(cfg.seed)

    # ------------------------------------------------------------ networks
    def _build_nets(self):
        import flax.linen as nn
        import jax
        import jax.numpy as jnp

        mc = self.config.model_config
        D, G, C, U = (
            mc["deter"], mc["stoch_groups"], mc["stoch_classes"],
            mc["units"],
        )
        self._G, self._C, self._D = G, C, D
        self.twohot = _TwoHot(mc["bins"])
        n_act = self._n_actions
        obs_dim = self._obs_dim

        class Nets(nn.Module):
            @nn.compact
            def __call__(self, mode, *args):
                return getattr(self, mode)(*args)

            def _mlp(self, x, out, name, layers=2):
                for i in range(layers):
                    x = nn.silu(
                        nn.LayerNorm(name=f"{name}_ln{i}")(
                            nn.Dense(U, name=f"{name}_d{i}")(x)
                        )
                    )
                return nn.Dense(
                    out,
                    name=f"{name}_out",
                    kernel_init=nn.initializers.variance_scaling(
                        0.1, "fan_in", "truncated_normal"
                    ),
                )(x)

            def encode(self, obs):
                return self._mlp(_symlog(obs), U, "enc")

            def seq(self, deter, stoch, action):
                x = jnp.concatenate(
                    [stoch.reshape(stoch.shape[0], G * C),
                     jax.nn.one_hot(action, n_act)],
                    -1,
                )
                x = nn.silu(
                    nn.LayerNorm(name="gru_in_ln")(
                        nn.Dense(U, name="gru_in")(x)
                    )
                )
                new_deter, _ = nn.GRUCell(D, name="gru")(deter, x)
                return new_deter

            def prior(self, deter):
                return self._mlp(deter, G * C, "prior").reshape(
                    (-1, G, C)
                )

            def posterior(self, deter, embed):
                x = jnp.concatenate([deter, embed], -1)
                return self._mlp(x, G * C, "post").reshape((-1, G, C))

            def decode(self, deter, stoch):
                x = jnp.concatenate(
                    [deter, stoch.reshape(stoch.shape[0], G * C)], -1
                )
                return self._mlp(x, obs_dim, "dec")

            def reward(self, deter, stoch):
                x = jnp.concatenate(
                    [deter, stoch.reshape(stoch.shape[0], G * C)], -1
                )
                return self._mlp(x, mc["bins"], "rew")

            def cont(self, deter, stoch):
                x = jnp.concatenate(
                    [deter, stoch.reshape(stoch.shape[0], G * C)], -1
                )
                return self._mlp(x, 1, "cont")[..., 0]

            def actor(self, deter, stoch):
                x = jnp.concatenate(
                    [deter, stoch.reshape(stoch.shape[0], G * C)], -1
                )
                return self._mlp(x, n_act, "actor")

            def critic(self, deter, stoch):
                x = jnp.concatenate(
                    [deter, stoch.reshape(stoch.shape[0], G * C)], -1
                )
                return self._mlp(x, mc["bins"], "critic")

        self.nets = Nets()
        import jax

        self._rng_key, k = jax.random.split(self._rng_key)
        obs0 = jnp.zeros((1, obs_dim))
        deter0 = jnp.zeros((1, D))
        stoch0 = jnp.zeros((1, G, C))
        params = self.nets.init(k, "encode", obs0)

        # Materialize every head's params once (deterministic per-mode
        # fold_in indices: seeded runs must reproduce).
        p = params
        for i, (mode, args) in enumerate(
            (
                ("seq", (deter0, stoch0, jnp.zeros((1,), jnp.int32))),
                ("prior", (deter0,)),
                ("posterior", (deter0, jnp.zeros((1, U)))),
                ("decode", (deter0, stoch0)),
                ("reward", (deter0, stoch0)),
                ("cont", (deter0, stoch0)),
                ("actor", (deter0, stoch0)),
                ("critic", (deter0, stoch0)),
            )
        ):
            out = self.nets.init(jax.random.fold_in(k, i + 1), mode, *args)
            p = {"params": {**p["params"], **out["params"]}}
        self.params = p
        self.slow_critic = jax.tree_util.tree_map(
            lambda x: x, self.params
        )
        # Return-normalization percentile EMA.
        self._ret_lo = 0.0
        self._ret_hi = 1.0

    # ----------------------------------------------------------- update fn
    def _build_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config
        G, C = self._G, self._C
        twohot = self.twohot
        nets = self.nets
        WM_PREFIXES = (
            "enc", "gru", "prior", "post", "dec", "rew", "cont",
        )

        def group_of(path_key: str) -> str:
            for pre in WM_PREFIXES:
                if path_key.startswith(pre):
                    return "wm"
            return "actor" if path_key.startswith("actor") else "critic"

        def label_tree(params):
            return {
                "params": {
                    k: group_of(k) for k in params["params"]
                }
            }

        tx = optax.multi_transform(
            {
                "wm": optax.chain(
                    optax.clip_by_global_norm(cfg.grad_clip),
                    optax.adam(cfg.lr),
                ),
                "actor": optax.chain(
                    optax.clip_by_global_norm(cfg.grad_clip),
                    optax.adam(cfg.actor_lr),
                ),
                "critic": optax.chain(
                    optax.clip_by_global_norm(cfg.grad_clip),
                    optax.adam(cfg.critic_lr),
                ),
            },
            label_tree(self.params),
        )
        self._tx = tx
        self.opt_state = tx.init(self.params)

        def unimix_sample(logits, key):
            probs = jax.nn.softmax(logits, -1)
            probs = 0.99 * probs + 0.01 / C  # 1% unimix
            logp = jnp.log(probs)
            idx = jax.random.categorical(key, logp, axis=-1)
            hot = jax.nn.one_hot(idx, C)
            # Straight-through gradients to the logits.
            return hot + probs - jax.lax.stop_gradient(probs), logp

        def kl_cat(logp_a, logp_b):
            pa = jnp.exp(logp_a)
            return jnp.sum(pa * (logp_a - logp_b), axis=(-2, -1))

        def loss_fn(params, slow_critic, batch, key, ret_lo, ret_hi):
            obs = batch["obs"]  # [B, T, obs]
            B, T = obs.shape[:2]
            acts = batch["actions"]
            is_first = batch["is_first"]

            embed = nets.apply(
                params, "encode", obs.reshape(B * T, -1)
            ).reshape(B, T, -1)

            def step(carry, inp):
                deter, stoch, k = carry
                emb_t, act_prev, first_t = inp
                k, k1 = jax.random.split(k)
                # Episode boundary: reset state (v3 resets to zeros).
                deter = deter * (1.0 - first_t)[:, None]
                stoch = stoch * (1.0 - first_t)[:, None, None]
                act_prev = (act_prev * (1.0 - first_t)).astype(jnp.int32)
                deter = nets.apply(params, "seq", deter, stoch, act_prev)
                prior_logits = nets.apply(params, "prior", deter)
                post_logits = nets.apply(
                    params, "posterior", deter, emb_t
                )
                stoch, _ = unimix_sample(post_logits, k1)
                return (deter, stoch, k), (
                    deter, stoch, prior_logits, post_logits
                )

            deter0 = jnp.zeros((B, self._D))
            stoch0 = jnp.zeros((B, G, C))
            act_prev = jnp.concatenate(
                [jnp.zeros((B, 1), acts.dtype), acts[:, :-1]], 1
            )
            key, kscan = jax.random.split(key)
            (_, _, _), (deters, stochs, priors, posts) = jax.lax.scan(
                step,
                (deter0, stoch0, kscan),
                (
                    embed.transpose(1, 0, 2),
                    act_prev.T,
                    is_first.T,
                ),
            )
            # [T, B, ...] -> flat [T*B, ...]
            TB = T * B
            deters_f = deters.reshape(TB, -1)
            stochs_f = stochs.reshape(TB, G, C)

            # ---- world-model losses
            dec = nets.apply(params, "decode", deters_f, stochs_f)
            obs_t = _symlog(obs.transpose(1, 0, 2).reshape(TB, -1))
            recon_loss = jnp.mean(jnp.sum((dec - obs_t) ** 2, -1))
            rew_logits = nets.apply(params, "reward", deters_f, stochs_f)
            rew_target = twohot.encode(
                batch["rewards"].T.reshape(TB)
            )
            reward_loss = -jnp.mean(
                jnp.sum(
                    rew_target
                    * jax.nn.log_softmax(rew_logits, -1),
                    -1,
                )
            )
            cont_logits = nets.apply(params, "cont", deters_f, stochs_f)
            cont_target = batch["continues"].T.reshape(TB)
            cont_loss = jnp.mean(
                optax.sigmoid_binary_cross_entropy(
                    cont_logits, cont_target
                )
            )

            def logp_unimix(logits):
                p = jax.nn.softmax(logits, -1)
                return jnp.log(0.99 * p + 0.01 / C)

            lp_post = logp_unimix(posts.reshape(TB, G, C))
            lp_prior = logp_unimix(priors.reshape(TB, G, C))
            sg = jax.lax.stop_gradient
            dyn = jnp.maximum(
                kl_cat(sg(lp_post), lp_prior), cfg.free_bits
            ).mean()
            rep = jnp.maximum(
                kl_cat(lp_post, sg(lp_prior)), cfg.free_bits
            ).mean()
            wm_loss = (
                recon_loss
                + reward_loss
                + cont_loss
                + cfg.dyn_loss_scale * dyn
                + cfg.rep_loss_scale * rep
            )

            # ---- imagination rollout from (sg) posterior states
            H = cfg.horizon
            img_deter = sg(deters_f)
            img_stoch = sg(stochs_f)

            # Frozen world model for behavior learning: actor/critic
            # gradients must not leak into the dynamics.
            pf = jax.tree_util.tree_map(sg, params)

            def img_step(carry, _):
                deter, stoch, k = carry
                k, k1, k2 = jax.random.split(k, 3)
                a_logits = nets.apply(params, "actor", deter, stoch)
                act = jax.random.categorical(k1, a_logits)
                new_deter = nets.apply(pf, "seq", deter, stoch, act)
                prior_logits = nets.apply(pf, "prior", new_deter)
                new_stoch, _ = unimix_sample(prior_logits, k2)
                return (new_deter, new_stoch, k), (
                    deter, stoch, act, a_logits
                )

            key, kimg = jax.random.split(key)
            (last_deter, last_stoch, _), (
                tr_deter, tr_stoch, tr_act, tr_logits
            ) = jax.lax.scan(
                img_step, (img_deter, img_stoch, kimg), None, length=H
            )
            # Heads over the imagined trajectory (+ bootstrap state).
            all_deter = jnp.concatenate(
                [tr_deter, last_deter[None]], 0
            ).reshape((H + 1) * TB, -1)
            all_stoch = jnp.concatenate(
                [tr_stoch, last_stoch[None]], 0
            ).reshape((H + 1) * TB, G, C)
            rew = twohot.decode(
                nets.apply(pf, "reward", all_deter, all_stoch)
            ).reshape(H + 1, TB)
            cont = jax.nn.sigmoid(
                nets.apply(pf, "cont", all_deter, all_stoch)
            ).reshape(H + 1, TB)
            val_logits = nets.apply(params, "critic", all_deter, all_stoch)
            values = twohot.decode(val_logits).reshape(H + 1, TB)
            slow_vals = twohot.decode(
                nets.apply(
                    slow_critic, "critic", sg(all_deter), sg(all_stoch)
                )
            ).reshape(H + 1, TB)

            disc = cfg.gamma * cont
            # Lambda returns, backwards.
            def lam_step(nxt, t):
                r_t = rew[t + 1]
                d_t = disc[t + 1]
                v_next = values[t + 1]
                ret = r_t + d_t * (
                    (1 - cfg.gae_lambda) * sg(v_next)
                    + cfg.gae_lambda * nxt
                )
                return ret, ret

            last = sg(values[H])
            _, rets = jax.lax.scan(
                lam_step, last, jnp.arange(H - 1, -1, -1)
            )
            returns = rets[::-1]  # [H, TB], target for values[0..H-1]
            returns = sg(returns)

            # Return normalization: percentile EMA scale.
            scale = jnp.maximum(ret_hi - ret_lo, 1.0)
            base_vals = values[:H]
            adv = (returns - base_vals) / scale

            a_logp_all = jax.nn.log_softmax(
                tr_logits.reshape(H * TB, -1), -1
            )
            act_logp = jnp.take_along_axis(
                a_logp_all, tr_act.reshape(H * TB, 1), 1
            )[:, 0].reshape(H, TB)
            entropy = -jnp.sum(
                jnp.exp(a_logp_all) * a_logp_all, -1
            ).reshape(H, TB)
            # Weight by in-horizon continuation probability.
            live = jnp.concatenate(
                [jnp.ones((1, TB)), jnp.cumprod(cont[:H], 0)[:-1]], 0
            )
            actor_loss = -jnp.mean(
                live * (sg(adv) * act_logp + cfg.entropy_coef * entropy)
            )

            # Critic: twohot CE to lambda returns + slow-critic reg.
            v_logits = val_logits.reshape(H + 1, TB, -1)[:H]
            ret_target = twohot.encode(returns)
            critic_ce = -jnp.sum(
                ret_target * jax.nn.log_softmax(v_logits, -1), -1
            )
            slow_target = twohot.encode(sg(slow_vals[:H]))
            critic_reg = -jnp.sum(
                slow_target * jax.nn.log_softmax(v_logits, -1), -1
            )
            critic_loss = jnp.mean(
                live * (critic_ce + cfg.critic_ema_reg * critic_reg)
            )

            total = wm_loss + actor_loss + critic_loss
            metrics = {
                "wm_loss": wm_loss,
                "recon_loss": recon_loss,
                "reward_loss": reward_loss,
                "cont_loss": cont_loss,
                "kl_dyn": dyn,
                "actor_loss": actor_loss,
                "critic_loss": critic_loss,
                "entropy": jnp.mean(entropy),
                "imagined_return_mean": jnp.mean(returns),
                "ret_p5": jnp.percentile(returns, 5.0),
                "ret_p95": jnp.percentile(returns, 95.0),
            }
            return total, metrics

        @jax.jit
        def update(params, slow_critic, opt_state, batch, key, lo, hi):
            (_, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params, slow_critic, batch, key, lo, hi)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            d = cfg.critic_ema_decay
            slow_critic = jax.tree_util.tree_map(
                lambda s, p: d * s + (1 - d) * p, slow_critic, params
            )
            return params, slow_critic, opt_state, metrics

        self._update = update

        @jax.jit
        def act(params, deter, stoch, obs, prev_action, first, key):
            k1, k2 = jax.random.split(key)
            B = obs.shape[0]
            deter = deter * (1.0 - first)[:, None]
            stoch = stoch * (1.0 - first)[:, None, None]
            prev_action = (prev_action * (1.0 - first)).astype(jnp.int32)
            deter = nets.apply(params, "seq", deter, stoch, prev_action)
            emb = nets.apply(params, "encode", obs)
            post = nets.apply(params, "posterior", deter, emb)
            stoch, _ = unimix_sample(post, k1)
            logits = nets.apply(params, "actor", deter, stoch)
            action = jax.random.categorical(k2, logits)
            return deter, stoch, action

        self._act = act

    # ---------------------------------------------------------- collection
    def _reset_collection(self):
        n = self.config.num_envs
        self._deter = np.zeros((n, self._D), np.float32)
        self._stoch = np.zeros((n, self._G, self._C), np.float32)
        self._prev_action = np.zeros((n,), np.int64)
        self._first = np.ones((n,), np.float32)
        self._cur_obs = []
        self._acc = []
        for i, env in enumerate(self._envs):
            obs, _ = env.reset(seed=(self.config.seed or 0) + i)
            self._cur_obs.append(np.asarray(obs, np.float32))
            self._acc.append(
                {"obs": [self._cur_obs[i]], "actions": [], "rewards": []}
            )

    def _collect(self, n_steps: int):
        import jax

        cfg = self.config
        steps = 0
        while steps < n_steps:
            self._rng_key, k = jax.random.split(self._rng_key)
            obs = np.stack(self._cur_obs)
            deter, stoch, action = self._act(
                self.params,
                self._deter,
                self._stoch,
                obs,
                self._prev_action,
                self._first,
                k,
            )
            self._deter = np.asarray(deter)
            self._stoch = np.asarray(stoch)
            actions = np.asarray(action)
            self._first = np.zeros_like(self._first)
            for i, env in enumerate(self._envs):
                o, r, term, trunc, _ = env.step(int(actions[i]))
                acc = self._acc[i]
                acc["actions"].append(int(actions[i]))
                acc["rewards"].append(float(r))
                acc["obs"].append(np.asarray(o, np.float32))
                steps += 1
                self._total_env_steps += 1
                if term or trunc:
                    self.replay.add(
                        acc["obs"], acc["actions"], acc["rewards"], term
                    )
                    self._ep_returns.append(float(np.sum(acc["rewards"])))
                    o, _ = self._envs[i].reset()
                    self._acc[i] = {
                        "obs": [np.asarray(o, np.float32)],
                        "actions": [],
                        "rewards": [],
                    }
                    self._first[i] = 1.0
                self._cur_obs[i] = np.asarray(o, np.float32)
            self._prev_action = actions

    # ------------------------------------------------------------ training
    def training_step(self) -> Dict[str, Any]:
        import jax

        cfg = self.config
        self._collect(cfg.sample_timesteps_per_iteration)
        if len(self.replay) < cfg.num_steps_sampled_before_learning_starts:
            return {"buffer_steps": float(len(self.replay))}
        n_updates = max(
            1,
            int(
                cfg.sample_timesteps_per_iteration
                * cfg.train_ratio
                / (cfg.batch_size_B * cfg.batch_length_T)
            ),
        )
        metrics_list = []
        for _ in range(n_updates):
            batch = self.replay.sample(
                cfg.batch_size_B, cfg.batch_length_T
            )
            self._rng_key, k = jax.random.split(self._rng_key)
            self.params, self.slow_critic, self.opt_state, m = (
                self._update(
                    self.params,
                    self.slow_critic,
                    self.opt_state,
                    batch,
                    k,
                    self._ret_lo,
                    self._ret_hi,
                )
            )
            self._updates += 1
            m = {k2: float(v) for k2, v in m.items()}
            # Percentile EMA of imagined returns (v3 return norm).
            self._ret_lo = 0.99 * self._ret_lo + 0.01 * m.pop("ret_p5")
            self._ret_hi = 0.99 * self._ret_hi + 0.01 * m.pop("ret_p95")
            metrics_list.append(m)
        out = {
            k2: float(np.mean([m[k2] for m in metrics_list]))
            for k2 in metrics_list[0]
        }
        out["buffer_steps"] = float(len(self.replay))
        out["num_updates"] = float(self._updates)
        return out

    def step(self) -> Dict[str, Any]:
        # Self-contained metrics (no shared env-runner group).
        learner_metrics = self.training_step()
        self._iteration = getattr(self, "_iteration", 0) + 1
        recent = self._ep_returns[-100:]
        return {
            "training_iteration": self._iteration,
            "num_env_steps_sampled_lifetime": self._total_env_steps,
            "episode_return_mean": (
                float(np.mean(recent)) if recent else float("nan")
            ),
            "learners": learner_metrics,
        }

    def train(self) -> Dict[str, Any]:
        return self.step()

    def evaluate(self, num_episodes: int = 10) -> Dict[str, Any]:
        recent = self._ep_returns[-num_episodes:]
        return {
            "episode_return_mean": (
                float(np.mean(recent)) if recent else float("nan")
            ),
            "num_episodes": len(recent),
        }

    def save_checkpoint(self, checkpoint_dir: str) -> str:
        import os
        import pickle

        import jax

        state = {
            "params": jax.device_get(self.params),
            "slow_critic": jax.device_get(self.slow_critic),
            "opt_state": jax.device_get(self.opt_state),
            "ret_lo": self._ret_lo,
            "ret_hi": self._ret_hi,
            "iteration": getattr(self, "_iteration", 0),
            "total_env_steps": self._total_env_steps,
            "updates": self._updates,
        }
        path = os.path.join(checkpoint_dir, "algorithm_state.pkl")
        with open(path, "wb") as f:
            pickle.dump(state, f)
        return checkpoint_dir

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        import os
        import pickle

        with open(
            os.path.join(checkpoint_dir, "algorithm_state.pkl"), "rb"
        ) as f:
            state = pickle.load(f)
        self.params = state["params"]
        self.slow_critic = state["slow_critic"]
        self.opt_state = state["opt_state"]
        self._ret_lo = state["ret_lo"]
        self._ret_hi = state["ret_hi"]
        self._iteration = state["iteration"]
        self._total_env_steps = state["total_env_steps"]
        self._updates = state["updates"]

    save = save_checkpoint
    restore = load_checkpoint

    def stop(self) -> None:
        for env in self._envs:
            env.close()
