"""AlgorithmConfig: fluent builder for RL algorithms.

Reference: rllib/algorithms/algorithm_config.py — chained
``.environment().env_runners().training().build()``. Each algorithm
subclasses it with algorithm-specific training knobs.
"""
from __future__ import annotations

import copy
from typing import Any, Callable, Dict, Optional, Type, Union

from ..core.rl_module import DiscretePolicyModule, RLModuleSpec


class AlgorithmConfig:
    algo_class: Optional[type] = None
    default_module_class: type = DiscretePolicyModule

    def __init__(self):
        # environment
        self.env: Union[str, Callable, None] = None
        self.env_config: Dict[str, Any] = {}
        # env runners
        self.num_env_runners = 0
        self.num_envs_per_env_runner = 1
        self.num_cpus_per_env_runner = 1
        self.rollout_fragment_length = 200
        # training
        self.lr = 3e-4
        self.gamma = 0.99
        self.train_batch_size = 4000
        self.minibatch_size: Optional[int] = None
        self.num_epochs = 1
        self.grad_clip: Optional[float] = None
        # learners
        self.num_learners = 0
        self.num_cpus_per_learner = 1
        self.num_tpus_per_learner = 0
        self.num_devices_per_learner = 1
        # module
        self.module_class: Optional[type] = None
        self.model_config: Dict[str, Any] = {}
        # misc
        self.seed: Optional[int] = None

    # ----------------------------------------------------------- builder
    def environment(self, env=None, *, env_config=None) -> "AlgorithmConfig":
        if env is not None:
            self.env = env
        if env_config is not None:
            self.env_config = dict(env_config)
        return self

    def env_runners(
        self,
        *,
        num_env_runners=None,
        num_envs_per_env_runner=None,
        num_cpus_per_env_runner=None,
        rollout_fragment_length=None,
    ) -> "AlgorithmConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if num_cpus_per_env_runner is not None:
            self.num_cpus_per_env_runner = num_cpus_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kwargs) -> "AlgorithmConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"Unknown training option {k!r}")
            setattr(self, k, v)
        return self

    def learners(
        self,
        *,
        num_learners=None,
        num_cpus_per_learner=None,
        num_tpus_per_learner=None,
        num_devices_per_learner=None,
    ) -> "AlgorithmConfig":
        if num_learners is not None:
            self.num_learners = num_learners
        if num_cpus_per_learner is not None:
            self.num_cpus_per_learner = num_cpus_per_learner
        if num_tpus_per_learner is not None:
            self.num_tpus_per_learner = num_tpus_per_learner
        if num_devices_per_learner is not None:
            self.num_devices_per_learner = num_devices_per_learner
        return self

    def rl_module(self, *, module_class=None, model_config=None) -> "AlgorithmConfig":
        if module_class is not None:
            self.module_class = module_class
        if model_config is not None:
            self.model_config = dict(model_config)
        return self

    def debugging(self, *, seed=None) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    # ------------------------------------------------------------- build
    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def module_spec(self, observation_space=None, action_space=None) -> RLModuleSpec:
        return RLModuleSpec(
            module_class=self.module_class or self.default_module_class,
            observation_space=observation_space,
            action_space=action_space,
            model_config=dict(self.model_config),
        )

    def env_runner_config(self, module_spec) -> Dict[str, Any]:
        return {
            "env": self.env,
            "env_config": self.env_config,
            "num_env_runners": self.num_env_runners,
            "num_envs_per_env_runner": self.num_envs_per_env_runner,
            "num_cpus_per_env_runner": self.num_cpus_per_env_runner,
            "rollout_fragment_length": self.rollout_fragment_length,
            "module_spec": module_spec,
            "seed": self.seed,
        }

    def learner_config(self) -> Dict[str, Any]:
        return {
            "lr": self.lr,
            "gamma": self.gamma,
            "minibatch_size": self.minibatch_size,
            "num_epochs": self.num_epochs,
            "grad_clip": self.grad_clip,
            "num_learners": self.num_learners,
            "num_cpus_per_learner": self.num_cpus_per_learner,
            "num_tpus_per_learner": self.num_tpus_per_learner,
            "num_devices_per_learner": self.num_devices_per_learner,
            "seed": self.seed,
        }

    def to_dict(self) -> Dict[str, Any]:
        return {k: v for k, v in self.__dict__.items()}

    def build(self):
        if self.algo_class is None:
            raise ValueError(f"{type(self).__name__}.algo_class not set")
        return self.algo_class(config=self)
