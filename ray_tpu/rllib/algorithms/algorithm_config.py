"""AlgorithmConfig: fluent builder for RL algorithms.

Reference: rllib/algorithms/algorithm_config.py — chained
``.environment().env_runners().training().build()``. Each algorithm
subclasses it with algorithm-specific training knobs.
"""
from __future__ import annotations

import copy
from typing import Any, Callable, Dict, Optional, Type, Union

from ..core.rl_module import DiscretePolicyModule, RLModuleSpec


class AlgorithmConfig:
    algo_class: Optional[type] = None
    default_module_class: type = DiscretePolicyModule

    def __init__(self):
        # environment
        self.env: Union[str, Callable, None] = None
        self.env_config: Dict[str, Any] = {}
        # env runners
        self.num_env_runners = 0
        self.num_envs_per_env_runner = 1
        self.num_cpus_per_env_runner = 1
        self.rollout_fragment_length = 200
        # training
        self.lr = 3e-4
        self.gamma = 0.99
        self.train_batch_size = 4000
        self.minibatch_size: Optional[int] = None
        self.num_epochs = 1
        self.grad_clip: Optional[float] = None
        # learners
        self.num_learners = 0
        self.num_cpus_per_learner = 1
        self.num_tpus_per_learner = 0
        self.num_devices_per_learner = 1
        # module
        self.module_class: Optional[type] = None
        self.model_config: Dict[str, Any] = {}
        # multi-agent (reference: AlgorithmConfig.multi_agent —
        # policies + policy_mapping_fn; None means single-agent)
        self.policies: Optional[Dict[str, Any]] = None
        self.policy_mapping_fn: Optional[Callable[[str], str]] = None
        # misc
        self.seed: Optional[int] = None

    # ----------------------------------------------------------- builder
    def environment(self, env=None, *, env_config=None) -> "AlgorithmConfig":
        if env is not None:
            self.env = env
        if env_config is not None:
            self.env_config = dict(env_config)
        return self

    def env_runners(
        self,
        *,
        num_env_runners=None,
        num_envs_per_env_runner=None,
        num_cpus_per_env_runner=None,
        rollout_fragment_length=None,
    ) -> "AlgorithmConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if num_cpus_per_env_runner is not None:
            self.num_cpus_per_env_runner = num_cpus_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kwargs) -> "AlgorithmConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"Unknown training option {k!r}")
            setattr(self, k, v)
        return self

    def learners(
        self,
        *,
        num_learners=None,
        num_cpus_per_learner=None,
        num_tpus_per_learner=None,
        num_devices_per_learner=None,
    ) -> "AlgorithmConfig":
        if num_learners is not None:
            self.num_learners = num_learners
        if num_cpus_per_learner is not None:
            self.num_cpus_per_learner = num_cpus_per_learner
        if num_tpus_per_learner is not None:
            self.num_tpus_per_learner = num_tpus_per_learner
        if num_devices_per_learner is not None:
            self.num_devices_per_learner = num_devices_per_learner
        return self

    def rl_module(self, *, module_class=None, model_config=None) -> "AlgorithmConfig":
        if module_class is not None:
            self.module_class = module_class
        if model_config is not None:
            self.model_config = dict(model_config)
        return self

    def multi_agent(
        self, *, policies=None, policy_mapping_fn=None
    ) -> "AlgorithmConfig":
        """Declare the policy modules and the agent→module mapping.

        ``policies``: dict {module_id: None | RLModuleSpec |
        (module_class, model_config)}. None uses the algorithm's
        default module. ``policy_mapping_fn(agent_id) -> module_id``
        must be picklable (module-level function / functools.partial)
        to ship to remote env runners.
        """
        if policies is not None:
            self.policies = dict(policies)
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        return self

    @property
    def is_multi_agent(self) -> bool:
        return bool(self.policies)

    def resolved_policy_mapping_fn(self):
        """The configured mapping, or a picklable default: all agents →
        the single module if there is exactly one, else agent_id ==
        module_id."""
        from ..env.multi_agent_env import ConstantMapping, agent_id_mapping

        if self.policy_mapping_fn is not None:
            return self.policy_mapping_fn
        if self.policies and len(self.policies) == 1:
            return ConstantMapping(next(iter(self.policies)))
        return agent_id_mapping

    def debugging(self, *, seed=None) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    # ------------------------------------------------------------- build
    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def module_spec(self, observation_space=None, action_space=None) -> RLModuleSpec:
        return RLModuleSpec(
            module_class=self.module_class or self.default_module_class,
            observation_space=observation_space,
            action_space=action_space,
            model_config=dict(self.model_config),
        )

    def multi_module_spec(self, env) -> "Any":
        """MultiRLModuleSpec with spaces probed from the multi-agent env
        (one representative agent per module)."""
        from ..core.multi_rl_module import MultiRLModuleSpec
        from ..core.rl_module import RLModuleSpec as _Spec

        mapping = self.resolved_policy_mapping_fn()
        specs: Dict[str, _Spec] = {}
        for mid, policy in (self.policies or {}).items():
            rep = next(
                (a for a in env.possible_agents if mapping(a) == mid), None
            )
            if rep is None:
                raise ValueError(f"no agent maps to module {mid!r}")
            if isinstance(policy, _Spec):
                spec = policy
                if spec.observation_space is None:
                    spec.observation_space = env.observation_space(rep)
                if spec.action_space is None:
                    spec.action_space = env.action_space(rep)
            else:
                cls, mcfg = (
                    policy
                    if isinstance(policy, tuple)
                    else (None, None)
                )
                spec = _Spec(
                    module_class=cls
                    or self.module_class
                    or self.default_module_class,
                    observation_space=env.observation_space(rep),
                    action_space=env.action_space(rep),
                    model_config=dict(mcfg or self.model_config),
                )
            specs[mid] = spec
        return MultiRLModuleSpec(specs)

    def env_runner_config(self, module_spec) -> Dict[str, Any]:
        cfg = {
            "env": self.env,
            "env_config": self.env_config,
            "num_env_runners": self.num_env_runners,
            "num_envs_per_env_runner": self.num_envs_per_env_runner,
            "num_cpus_per_env_runner": self.num_cpus_per_env_runner,
            "rollout_fragment_length": self.rollout_fragment_length,
            "module_spec": module_spec,
            "seed": self.seed,
        }
        if self.is_multi_agent:
            from ..env.multi_agent_env_runner import MultiAgentEnvRunner

            cfg["runner_cls"] = MultiAgentEnvRunner
            cfg["policy_mapping_fn"] = self.resolved_policy_mapping_fn()
        return cfg

    def learner_config(self) -> Dict[str, Any]:
        return {
            "lr": self.lr,
            "gamma": self.gamma,
            "minibatch_size": self.minibatch_size,
            "num_epochs": self.num_epochs,
            "grad_clip": self.grad_clip,
            "num_learners": self.num_learners,
            "num_cpus_per_learner": self.num_cpus_per_learner,
            "num_tpus_per_learner": self.num_tpus_per_learner,
            "num_devices_per_learner": self.num_devices_per_learner,
            "seed": self.seed,
        }

    def to_dict(self) -> Dict[str, Any]:
        return {k: v for k, v in self.__dict__.items()}

    def build(self):
        if self.algo_class is None:
            raise ValueError(f"{type(self).__name__}.algo_class not set")
        return self.algo_class(config=self)
