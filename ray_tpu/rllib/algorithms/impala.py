"""IMPALA: asynchronous sampling + V-trace off-policy correction.

Reference: rllib/algorithms/impala/impala.py:530 — env runners sample
continuously (no barrier with the learner); sample batches carry the
behavior policy's logp, and the learner corrects the policy lag with
V-trace (Espeholt et al. 2018) clipped importance weights. The learner
update is one jitted program; the V-trace recursion is a
``lax.scan`` over time (XLA-friendly — no python loop over T).
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Tuple

import numpy as np

import ray_tpu

from ..core.learner import Learner
from ..core.rl_module import Columns
from .algorithm import Algorithm
from .algorithm_config import AlgorithmConfig


class IMPALAConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        # Defaults follow the reference's tuned CartPole config
        # (rllib/tuned_examples/impala/cartpole_impala.py): small vf
        # coefficient and global-norm grad clipping are what keep
        # V-trace from collapsing the policy early.
        self.lr = 5e-4
        self.train_batch_size = 500
        self.grad_clip = 40.0
        self.vf_loss_coeff = 0.05
        self.entropy_coeff = 0.005
        self.vtrace_clip_rho_threshold = 1.0
        self.vtrace_clip_c_threshold = 1.0
        # Standardize PG advantages within the batch. Not in the
        # original V-trace, but it prevents the early positive-feedback
        # policy collapse when every reward is positive and the value
        # net hasn't converged yet.
        self.standardize_advantages = True
        self.rollout_fragment_length = 50
        self.num_env_runners = 2
        self.max_requests_in_flight_per_env_runner = 2
        self.broadcast_interval = 1  # sync weights every N learner steps

    @property
    def algo_class(self):
        return IMPALA

    def learner_config(self):
        cfg = super().learner_config()
        cfg.update(
            gamma=self.gamma,
            vf_loss_coeff=self.vf_loss_coeff,
            entropy_coeff=self.entropy_coeff,
            vtrace_clip_rho_threshold=self.vtrace_clip_rho_threshold,
            vtrace_clip_c_threshold=self.vtrace_clip_c_threshold,
            rollout_fragment_length=self.rollout_fragment_length,
            standardize_advantages=self.standardize_advantages,
        )
        return cfg


def _pad_episodes(episodes, T: int):
    """Episodes → [B, T] padded arrays + mask (static shapes for XLA)."""
    cols = {
        "obs": [],
        "actions": [],
        "rewards": [],
        "terminateds": [],
        "action_logp": [],
        "bootstrap_obs": [],
        "mask": [],
    }
    for ep in episodes:
        L = min(len(ep), T)
        obs = np.asarray(ep.observations, np.float32)
        pad = T - L
        cols["obs"].append(
            np.concatenate([obs[:L], np.zeros((pad,) + obs.shape[1:], np.float32)])
        )
        cols["bootstrap_obs"].append(obs[L])
        acts = np.asarray(ep.actions[:L])
        cols["actions"].append(np.concatenate([acts, np.zeros(pad, acts.dtype)]))
        rew = np.asarray(ep.rewards[:L], np.float32)
        cols["rewards"].append(np.concatenate([rew, np.zeros(pad, np.float32)]))
        term = np.zeros(T, np.float32)
        if ep.is_terminated and L == len(ep):
            term[L - 1] = 1.0
        cols["terminateds"].append(term)
        logp = np.asarray(ep.extra_model_outputs["action_logp"][:L], np.float32)
        cols["action_logp"].append(np.concatenate([logp, np.zeros(pad, np.float32)]))
        mask = np.zeros(T, np.float32)
        mask[:L] = 1.0
        cols["mask"].append(mask)
    return {k: np.stack(v) for k, v in cols.items()}


class IMPALALearner(Learner):
    def build(self):
        super().build()
        self.config.setdefault("minibatch_size", None)
        self.config["num_epochs"] = 1

    def build_batch(self, episodes) -> Dict[str, np.ndarray]:
        batch = _pad_episodes(episodes, self.config["rollout_fragment_length"])
        # Pad the batch dim to a multiple of 8 (mask=0 rows) so XLA sees
        # a handful of shapes, not one compile per episode count.
        B = len(episodes)
        pad = (-B) % 8
        if pad:
            for k, v in batch.items():
                batch[k] = np.concatenate(
                    [v, np.zeros((pad,) + v.shape[1:], v.dtype)]
                )
        return batch

    def compute_loss(self, params, batch, rng) -> Tuple[Any, Dict[str, Any]]:
        import jax
        import jax.numpy as jnp

        cfg = self.config
        B, T = batch["actions"].shape
        obs_flat = batch["obs"].reshape((B * T,) + batch["obs"].shape[2:])
        out = self.module.forward_train(params, {Columns.OBS: obs_flat})
        logits = out[Columns.ACTION_DIST_INPUTS].reshape(B, T, -1)
        values = out[Columns.VF_PREDS].reshape(B, T)
        bootstrap = self.module.compute_values(params, batch["bootstrap_obs"])

        z = logits - jax.scipy.special.logsumexp(logits, axis=-1, keepdims=True)
        actions = batch["actions"].astype(jnp.int32)
        target_logp = jnp.take_along_axis(z, actions[..., None], axis=-1)[..., 0]

        mask = batch["mask"]
        # The V-trace targets are pure *targets*: no gradient may flow
        # through rho/c/bootstrap into the policy (rho = exp(pi - mu)
        # carries d/d_logits even when numerically 1 on-policy; leaking
        # it through the value loss silently corrupts the policy).
        rho = jax.lax.stop_gradient(
            jnp.exp(target_logp - batch["action_logp"])
        )
        rho_clip = jnp.minimum(rho, cfg["vtrace_clip_rho_threshold"])
        c_clip = jnp.minimum(rho, cfg["vtrace_clip_c_threshold"])
        bootstrap = jax.lax.stop_gradient(bootstrap)
        discounts = cfg["gamma"] * (1.0 - batch["terminateds"]) * mask

        values_stop = jax.lax.stop_gradient(values)
        # next-step value: V(s_{t+1}) while t+1 is still a valid step of
        # this chunk, else the bootstrap value V(s_L) (for rows shorter
        # than T, position t+1 holds padding, not the next obs).
        next_valid = jnp.concatenate(
            [mask[:, 1:], jnp.zeros_like(mask[:, :1])], axis=1
        )
        v_shift = jnp.concatenate(
            [values_stop[:, 1:], jnp.zeros_like(bootstrap)[:, None]], axis=1
        )
        v_tp1 = next_valid * v_shift + (1.0 - next_valid) * bootstrap[:, None]
        deltas = mask * rho_clip * (
            batch["rewards"] + discounts * v_tp1 - values_stop
        )

        def scan_fn(acc, xs):
            delta_t, disc_t, c_t = xs
            acc = delta_t + disc_t * c_t * acc
            return acc, acc

        # Reverse-time scan over T (time-major for scan).
        _, acc = jax.lax.scan(
            scan_fn,
            jnp.zeros((B,), values.dtype),
            (deltas.T, discounts.T, c_clip.T),
            reverse=True,
        )
        vs = values_stop + acc.T
        vs_shift = jnp.concatenate(
            [vs[:, 1:], jnp.zeros_like(bootstrap)[:, None]], axis=1
        )
        vs_tp1 = next_valid * vs_shift + (1.0 - next_valid) * bootstrap[:, None]
        pg_adv = jax.lax.stop_gradient(
            rho_clip * (batch["rewards"] + discounts * vs_tp1 - values_stop)
        )

        denom = jnp.maximum(mask.sum(), 1.0)
        if cfg.get("standardize_advantages", True):
            adv_mean = jnp.sum(pg_adv * mask) / denom
            adv_var = jnp.sum(jnp.square(pg_adv - adv_mean) * mask) / denom
            pg_adv = (pg_adv - adv_mean) / jnp.maximum(
                jnp.sqrt(adv_var), 1e-4
            )
        policy_loss = -jnp.sum(target_logp * pg_adv * mask) / denom
        vf_loss = 0.5 * jnp.sum(jnp.square(vs - values) * mask) / denom
        entropy = -jnp.sum(jnp.exp(z) * z * mask[..., None]) / denom
        total = (
            policy_loss
            + cfg["vf_loss_coeff"] * vf_loss
            - cfg["entropy_coeff"] * entropy
        )
        return total, {
            "policy_loss": policy_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
            "mean_rho": jnp.sum(rho * mask) / denom,
        }


class IMPALA(Algorithm):
    learner_class = IMPALALearner

    def setup(self, config_dict) -> None:
        super().setup(config_dict)
        self._inflight: Dict[Any, int] = {}  # ref -> actor index
        self._learner_steps = 0
        self._episode_buffer: List = []  # accumulate to train_batch_size
        self._buffered_steps = 0

    def _runner_sample_async(self, idx: int):
        mgr = self.env_runner_group._manager
        actor = mgr.actor(idx)
        frag = self.config.rollout_fragment_length
        n_envs = self.config.num_envs_per_env_runner
        ref = actor.sample.remote(num_timesteps=frag * n_envs)
        self._inflight[ref] = idx

    def training_step(self) -> Dict[str, Any]:
        if self.env_runner_group._manager is None:
            # Synchronous degenerate mode (num_env_runners=0): still
            # V-trace, but no async pipeline.
            episodes = self.env_runner_group.sample(
                num_timesteps=self.config.train_batch_size
            )
            self._record_episodes(episodes)
            metrics = self.learner_group.update_from_episodes(episodes)
            self.env_runner_group.sync_weights(self.learner_group.get_weights())
            return metrics

    # ---- async path: keep every runner busy; learn on arrival ----
        mgr = self.env_runner_group._manager
        in_flight_target = self.config.max_requests_in_flight_per_env_runner
        for idx in mgr.healthy_actor_ids():
            while (
                sum(1 for i in self._inflight.values() if i == idx)
                < in_flight_target
            ):
                self._runner_sample_async(idx)
        ready, _ = ray_tpu.wait(
            list(self._inflight), num_returns=1, timeout=60.0
        )
        all_metrics: List[Dict[str, Any]] = []
        updated_runners = []
        for ref in ready:
            idx = self._inflight.pop(ref)
            try:
                episodes = ray_tpu.get(ref)
            except Exception:
                # Runner died: drop its OTHER in-flight refs too, or a
                # stale ref failing later would restart (kill) the
                # healthy replacement actor.
                for stale in [
                    r for r, i in self._inflight.items() if i == idx
                ]:
                    del self._inflight[stale]
                mgr._restart(idx)
                continue
            self._record_episodes(episodes)
            self._episode_buffer.extend(episodes)
            self._buffered_steps += sum(len(e) for e in episodes)
            if self._buffered_steps >= self.config.train_batch_size:
                all_metrics.append(
                    self.learner_group.update_from_episodes(
                        self._episode_buffer
                    )
                )
                self._episode_buffer = []
                self._buffered_steps = 0
                self._learner_steps += 1
            updated_runners.append(idx)
            self._runner_sample_async(idx)
        if all_metrics and self._learner_steps % self.config.broadcast_interval == 0:
            w_ref = ray_tpu.put(self.learner_group.get_weights())
            for idx in set(updated_runners):
                mgr.actor(idx).set_weights.remote(w_ref)
        if not all_metrics:
            return {}
        return {
            k: float(np.mean([m[k] for m in all_metrics]))
            for k in all_metrics[0]
        }
