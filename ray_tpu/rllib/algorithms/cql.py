"""CQL: conservative Q-learning for offline RL.

Reference: rllib/algorithms/cql/ — SAC's actor/twin-critic/temperature
machinery trained purely from a recorded dataset, with the CQL(H)
conservative regularizer pushing Q down on out-of-distribution actions
(logsumexp over random + policy actions, importance-corrected) and up
on dataset actions, plus a behavior-cloning warm-start for the actor.
Rides the same offline IO as MARWIL/BC and the SAC learner's combined
single-jit update; the conservative term adds only batched MXU matmuls
(tiled (s, a') critic sweeps), so the whole step stays one device
program.
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np

from ..core.rl_module import Columns
from ..utils.replay_buffers import ReplayBuffer
from .sac import SAC, SACConfig, SACLearner


class CQLConfig(SACConfig):
    def __init__(self):
        super().__init__()
        self.input_: Any = None  # offline sample dir (rllib "input")
        # CQL(H) knobs (reference: cql/cql.py defaults).
        self.cql_n_actions = 4  # sampled actions per source per state
        self.min_q_weight = 5.0
        self.bc_iters = 200  # actor warm-start: BC before SAC objective
        self.num_steps_sampled_before_learning_starts = 0

    @property
    def algo_class(self):
        return CQL

    def offline_data(self, *, input_=None) -> "CQLConfig":
        if input_ is not None:
            self.input_ = input_
        return self

    def learner_config(self):
        cfg = super().learner_config()
        cfg.update(
            cql_n_actions=self.cql_n_actions,
            min_q_weight=self.min_q_weight,
        )
        return cfg


class CQLLearner(SACLearner):
    """SAC losses + the conservative regularizer; `bc_phase` rides in
    the batch as a traced scalar so warm-start vs SAC actor objectives
    switch without recompiling."""

    def compute_loss(self, params, batch, rng) -> Tuple[Any, Dict[str, Any]]:
        import jax
        import jax.numpy as jnp

        cfg = self.config
        stop = jax.lax.stop_gradient
        total, metrics = super().compute_loss(params, batch, rng)

        obs = batch[Columns.OBS]
        next_obs = batch[Columns.NEXT_OBS]
        actions = batch[Columns.ACTIONS]
        if actions.ndim == 1:
            actions = actions[:, None]
        B = obs.shape[0]
        A = self.module.num_actions()
        N = int(cfg["cql_n_actions"])
        rng_r, rng_pi, rng_pi2 = jax.random.split(
            jax.random.fold_in(rng, 1), 3
        )

        def tile(x):
            return jnp.repeat(x, N, axis=0)  # [N*B, ...]

        scale = jnp.asarray(self.module.action_scale, jnp.float32)
        center = jnp.asarray(self.module.action_center, jnp.float32)

        # Random actions, importance-corrected by the uniform density.
        rand_a = (
            jax.random.uniform(rng_r, (N * B, A), minval=-1.0, maxval=1.0)
            * scale
            + center
        )
        logp_rand = -jnp.sum(jnp.log(2.0 * scale))
        # Policy actions at s and s' (reparameterized, density-corrected).
        a_pi, logp_pi = self.module.sample_action(params, tile(obs), rng_pi)
        a_pi2, logp_pi2 = self.module.sample_action(
            params, tile(next_obs), rng_pi2
        )

        def cat_q(qname):
            frozen = {qname: params[qname]}

            def q(o, a):
                oa = jnp.concatenate(
                    [o.reshape(o.shape[0], -1), a], axis=-1
                )
                return getattr(self.module, f"_{qname}").apply(
                    frozen[qname], oa
                )[..., 0]

            # tile() = repeat along axis 0: flat index k = b*N + n, so
            # the [N, B] view is reshape(B, N).T — reshape(N, B) would
            # mix DIFFERENT states into one logsumexp column.
            def nb(v):
                return v.reshape(B, N).T

            q_rand = nb(q(tile(obs), rand_a)) - logp_rand
            q_p = nb(q(tile(obs), stop(a_pi))) - nb(stop(logp_pi))
            q_p2 = nb(q(tile(obs), stop(a_pi2))) - nb(stop(logp_pi2))
            return jnp.concatenate([q_rand, q_p, q_p2], axis=0)  # [3N, B]

        q1_data, q2_data = self.module.q_values(params, obs, actions)
        cql1 = jnp.mean(
            jax.scipy.special.logsumexp(cat_q("q1"), axis=0) - q1_data
        )
        cql2 = jnp.mean(
            jax.scipy.special.logsumexp(cat_q("q2"), axis=0) - q2_data
        )
        conservative = cfg["min_q_weight"] * (cql1 + cql2)

        # BC warm-start: replace the SAC actor objective with the
        # dataset-action log-likelihood for the first bc_iters updates
        # (bc_phase is 1.0 then 0.0 — a traced scalar, no recompile).
        bc_phase = batch.get("bc_phase", jnp.asarray(0.0))
        dist = self.module._pi.apply(params["pi"], obs)
        mean, log_std = jnp.split(dist, 2, axis=-1)
        log_std = jnp.clip(log_std, -20.0, 2.0)
        # Invert the tanh squash on dataset actions (clipped for
        # numerical safety at the bounds).
        u_data = jnp.arctanh(
            jnp.clip((actions - center) / scale, -0.999999, 0.999999)
        )
        bc_logp = jnp.sum(
            -0.5 * jnp.square((u_data - mean) / jnp.exp(log_std))
            - log_std
            - 0.5 * jnp.log(2.0 * jnp.pi),
            axis=-1,
        )
        bc_loss = -jnp.mean(bc_logp)
        # total already includes the SAC actor loss; fade it out during
        # the BC phase by adding (bc - actor) weighted by bc_phase.
        total = total + conservative + bc_phase * (
            bc_loss - metrics["actor_loss"]
        )
        metrics.update(
            cql_loss=conservative, bc_loss=bc_loss, bc_phase=bc_phase
        )
        return total, metrics


class CQL(SAC):
    """Offline: the replay buffer is loaded once from the dataset and
    the env runners are used only by evaluate()."""

    learner_class = CQLLearner

    def setup(self, config_dict) -> None:
        super().setup(config_dict)
        cfg = self.config
        if not cfg.input_:
            raise ValueError(
                "CQL is an offline algorithm: set "
                "config.offline_data(input_=<sample dir>)"
            )
        from ..offline import SampleReader

        episodes = SampleReader(cfg.input_, seed=cfg.seed).read_all()
        # Offline training wants the whole dataset resident; grow past
        # the configured capacity only as far as the data requires.
        n_transitions = sum(len(ep) for ep in episodes)
        self.replay = ReplayBuffer(
            max(cfg.replay_buffer_capacity, n_transitions), seed=cfg.seed
        )
        self.replay.add_episodes(episodes)
        self._updates = 0

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        assert self.learner_group.is_local
        learner: CQLLearner = self.learner_group._local
        metrics_list = []
        for _ in range(cfg.updates_per_iteration):
            batch = self.replay.sample(cfg.train_batch_size)
            batch.pop("batch_indexes", None)
            batch["bc_phase"] = np.float32(
                1.0 if self._updates < cfg.bc_iters else 0.0
            )
            metrics_list.append(learner.update(dict(batch)))
            self._updates += 1
        # No env sampling during training; evaluate() syncs weights.
        return {
            k: float(np.mean([m[k] for m in metrics_list]))
            for k in metrics_list[0]
        }

    def evaluate(self, num_episodes: int = 10) -> Dict[str, Any]:
        self.env_runner_group.sync_weights(self.learner_group.get_weights())
        episodes = self.env_runner_group.sample(
            num_episodes=num_episodes, explore=False
        )
        returns = [float(np.sum(ep.rewards)) for ep in episodes]
        return {
            "episode_return_mean": float(np.mean(returns)),
            "num_episodes": len(returns),
        }
