"""PPO: clipped-surrogate policy optimization.

Reference: rllib/algorithms/ppo/ppo.py (training_step:419) +
ppo_torch_learner loss. training_step = synchronous parallel sampling →
learner update (GAE + N epochs of minibatch SGD, all one jitted
program) → weight broadcast to env runners.
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np

from ..connectors.connector_v2 import EpisodesToBatch, GeneralAdvantageEstimation
from ..core.learner import Learner
from ..core.rl_module import Columns
from .algorithm import Algorithm
from .algorithm_config import AlgorithmConfig


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lambda_ = 0.95
        self.clip_param = 0.2
        self.vf_clip_param = 10.0
        self.vf_loss_coeff = 1.0
        self.entropy_coeff = 0.0
        self.kl_coeff = 0.0  # adaptive-KL off by default (clip does the work)
        self.kl_target = 0.01
        self.num_epochs = 8
        self.minibatch_size = 128
        self.train_batch_size = 2000
        self.lr = 5e-4

    @property
    def algo_class(self):
        return PPO

    def learner_config(self):
        cfg = super().learner_config()
        cfg.update(
            lambda_=self.lambda_,
            clip_param=self.clip_param,
            vf_clip_param=self.vf_clip_param,
            vf_loss_coeff=self.vf_loss_coeff,
            entropy_coeff=self.entropy_coeff,
            kl_coeff=self.kl_coeff,
            gamma=self.gamma,
        )
        return cfg


class PPOLearner(Learner):
    """Loss matches the reference PPO learner: clipped surrogate +
    clipped value loss + entropy bonus (ppo/torch/ppo_torch_learner.py)."""

    def build(self):
        super().build()
        self._batch_pipeline = EpisodesToBatch()

    def build_batch(self, episodes) -> Dict[str, np.ndarray]:
        batch = self._batch_pipeline(episodes=episodes)
        gae = GeneralAdvantageEstimation(
            gamma=self.config["gamma"],
            lambda_=self.config["lambda_"],
            values_fn=self._batched_values,
        )
        batch = gae(batch=batch, episodes=episodes)
        # Advantage standardization (reference: PPO's
        # standardize_fields=["advantages"]).
        adv = batch[Columns.ADVANTAGES]
        batch[Columns.ADVANTAGES] = (adv - adv.mean()) / max(adv.std(), 1e-4)
        return batch

    def _batched_values(self, obs_list):
        """Value net over ALL episodes in one jitted call, padded to a
        bucket size so XLA compiles once, not once per episode length."""
        import jax
        import numpy as np_

        if not hasattr(self, "_value_jit_fn"):
            self._value_jit_fn = jax.jit(self.module.compute_values)
        lens = [len(o) for o in obs_list]
        flat = np_.concatenate(obs_list)
        bucket = 512
        padded_len = ((len(flat) + bucket - 1) // bucket) * bucket
        pad = np_.zeros((padded_len - len(flat),) + flat.shape[1:], flat.dtype)
        values = jax.device_get(
            self._value_jit_fn(self.params, np_.concatenate([flat, pad]))
        )[: len(flat)]
        out, off = [], 0
        for L in lens:
            out.append(values[off : off + L])
            off += L
        return out

    def compute_loss(self, params, batch, rng) -> Tuple[Any, Dict[str, Any]]:
        import jax.numpy as jnp

        cfg = self.config
        out = self.module.forward_train(params, batch)
        logits = out[Columns.ACTION_DIST_INPUTS]
        logp_all = _log_softmax(logits)
        actions = batch[Columns.ACTIONS].astype(jnp.int32)
        logp = jnp.take_along_axis(logp_all, actions[:, None], axis=-1)[:, 0]
        ratio = jnp.exp(logp - batch[Columns.ACTION_LOGP])
        adv = batch[Columns.ADVANTAGES]
        surrogate = jnp.minimum(
            adv * ratio,
            adv * jnp.clip(ratio, 1 - cfg["clip_param"], 1 + cfg["clip_param"]),
        )
        policy_loss = -jnp.mean(surrogate)

        vf = out[Columns.VF_PREDS]
        vf_err = jnp.square(vf - batch[Columns.VALUE_TARGETS])
        vf_loss = jnp.mean(jnp.clip(vf_err, 0, cfg["vf_clip_param"]))

        entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
        total = (
            policy_loss
            + cfg["vf_loss_coeff"] * vf_loss
            - cfg["entropy_coeff"] * entropy
        )
        mean_kl = jnp.mean(batch[Columns.ACTION_LOGP] - logp)
        if cfg.get("kl_coeff"):
            total = total + cfg["kl_coeff"] * mean_kl
        return total, {
            "policy_loss": policy_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
            "mean_kl": mean_kl,
        }


def _log_softmax(logits):
    import jax.numpy as jnp

    z = logits - jnp.max(logits, axis=-1, keepdims=True)
    return z - jnp.log(jnp.sum(jnp.exp(z), axis=-1, keepdims=True))


class PPO(Algorithm):
    learner_class = PPOLearner

    def training_step(self) -> Dict[str, Any]:
        episodes = self.env_runner_group.sample(
            num_timesteps=self.config.train_batch_size
        )
        self._record_episodes(episodes)
        metrics = self.learner_group.update_from_episodes(episodes)
        self.env_runner_group.sync_weights(self.learner_group.get_weights())
        return metrics
