"""DQN / double-DQN with (prioritized) replay.

Reference: rllib/algorithms/dqn/ — sample with ε-greedy exploration
into a replay buffer; update on uniform or PER samples with a target
network refreshed every N steps; double-Q action selection by the
online net.
"""
from __future__ import annotations

import pickle
from typing import Any, Dict, Tuple

import numpy as np

from ..connectors.connector_v2 import (
    BatchObservations,
    ConnectorPipelineV2,
    EpsilonGreedyActions,
)
from ..core.learner import Learner
from ..core.rl_module import Columns, QNetworkModule
from ..utils.replay_buffers import PrioritizedReplayBuffer, ReplayBuffer
from .algorithm import Algorithm
from .algorithm_config import AlgorithmConfig


class DQNConfig(AlgorithmConfig):
    default_module_class = QNetworkModule

    def __init__(self):
        super().__init__()
        self.lr = 5e-4
        self.train_batch_size = 32
        self.replay_buffer_capacity = 50_000
        self.prioritized_replay = False
        self.per_alpha = 0.6
        self.per_beta = 0.4
        self.num_steps_sampled_before_learning_starts = 1000
        self.target_network_update_freq = 500
        self.double_q = True
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.05
        self.epsilon_timesteps = 10_000
        self.sample_timesteps_per_iteration = 400
        self.updates_per_iteration = 100

    @property
    def algo_class(self):
        return DQN

    def learner_config(self):
        cfg = super().learner_config()
        cfg.update(
            gamma=self.gamma,
            double_q=self.double_q,
            # minibatching handled by replay sampling
            minibatch_size=None,
            num_epochs=1,
            target_updates_every=max(
                1,
                self.target_network_update_freq
                // max(1, self.train_batch_size),
            ),
        )
        return cfg


class DQNLearner(Learner):
    def build(self):
        super().build()
        import jax

        self.target_params = jax.device_get(self.params)
        self._updates = 0

    def build_batch(self, episodes):
        from ..connectors.connector_v2 import EpisodesToBatch

        return EpisodesToBatch()(episodes=episodes)

    def compute_loss(self, params, batch, rng) -> Tuple[Any, Dict[str, Any]]:
        import jax.numpy as jnp

        cfg = self.config
        q_all = self.module.forward_train(params, batch)["q_values"]
        actions = batch[Columns.ACTIONS].astype(jnp.int32)
        q = jnp.take_along_axis(q_all, actions[:, None], axis=-1)[:, 0]

        # Target params ride in the batch as a jit argument (a captured
        # self.target_params would bake into the compiled program and
        # force a recompile at every target sync).
        next_batch = {Columns.OBS: batch[Columns.NEXT_OBS]}
        q_next_target = self.module.forward_train(
            batch["target_params"], next_batch
        )["q_values"]
        if cfg.get("double_q", True):
            q_next_online = self.module.forward_train(params, next_batch)[
                "q_values"
            ]
            best = jnp.argmax(q_next_online, axis=-1)
            q_next = jnp.take_along_axis(q_next_target, best[:, None], axis=-1)[
                :, 0
            ]
        else:
            q_next = jnp.max(q_next_target, axis=-1)
        q_next = jax_stop_gradient(q_next)
        target = (
            batch[Columns.REWARDS]
            + cfg["gamma"] * (1.0 - batch[Columns.TERMINATEDS]) * q_next
        )
        td = q - target
        weights = batch.get("weights")
        loss = jnp.mean(
            (weights if weights is not None else 1.0) * huber(td)
        )
        return loss, {"qf_mean": jnp.mean(q), "td_error_abs": jnp.mean(jnp.abs(td))}

    def update(self, batch):
        # Refresh target net on schedule (counted in update calls).
        batch = dict(batch, target_params=self.target_params)
        metrics = super().update(batch)
        self._updates += 1
        if self._updates % max(
            1, self.config.get("target_updates_every", 10)
        ) == 0:
            import jax

            self.target_params = jax.device_get(self.params)
        return metrics

    def td_errors(self, batch) -> np.ndarray:
        """|TD| per transition for PER priority updates."""
        import jax
        import jax.numpy as jnp

        if not hasattr(self, "_td_jit"):

            def f(params, target_params, batch):
                q_all = self.module.forward_train(params, batch)["q_values"]
                actions = batch[Columns.ACTIONS].astype(jnp.int32)
                q = jnp.take_along_axis(q_all, actions[:, None], axis=-1)[:, 0]
                nb = {Columns.OBS: batch[Columns.NEXT_OBS]}
                qt = self.module.forward_train(target_params, nb)["q_values"]
                qn = jnp.max(qt, axis=-1)
                target = (
                    batch[Columns.REWARDS]
                    + self.config["gamma"]
                    * (1.0 - batch[Columns.TERMINATEDS])
                    * qn
                )
                return jnp.abs(q - target)

            self._td_jit = jax.jit(f)
        return np.asarray(
            jax.device_get(
                self._td_jit(self.params, self.target_params, batch)
            )
        )


class _EpsilonSchedule(EpsilonGreedyActions):
    """Linear ε decay; picklable (lambdas can't ship to runner actors)."""

    def __init__(self, eps0: float, eps1: float, horizon: int):
        self.eps0, self.eps1, self.horizon = eps0, eps1, horizon
        super().__init__(self._eps)

    def _eps(self, step: int) -> float:
        return max(
            self.eps1,
            self.eps0 - (self.eps0 - self.eps1) * step / self.horizon,
        )


def jax_stop_gradient(x):
    import jax

    return jax.lax.stop_gradient(x)


def huber(x, delta: float = 1.0):
    import jax.numpy as jnp

    ax = jnp.abs(x)
    return jnp.where(ax <= delta, 0.5 * x * x, delta * (ax - 0.5 * delta))


class DQN(Algorithm):
    learner_class = DQNLearner

    def setup(self, config_dict) -> None:
        super().setup(config_dict)
        cfg = self.config
        if cfg.prioritized_replay:
            self.replay = PrioritizedReplayBuffer(
                cfg.replay_buffer_capacity,
                alpha=cfg.per_alpha,
                beta=cfg.per_beta,
                seed=cfg.seed,
            )
        else:
            self.replay = ReplayBuffer(cfg.replay_buffer_capacity, seed=cfg.seed)

    def env_runner_config(self) -> Dict[str, Any]:
        # ε-greedy exploration schedule lives in the runner's
        # module-to-env connector.
        cfg = self.config
        eps0, eps1, T = (
            cfg.epsilon_initial,
            cfg.epsilon_final,
            cfg.epsilon_timesteps,
        )
        runner_cfg = super().env_runner_config()
        runner_cfg["module_to_env"] = ConnectorPipelineV2(
            [_EpsilonSchedule(eps0, eps1, T)]
        )
        return runner_cfg

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        episodes = self.env_runner_group.sample(
            num_timesteps=cfg.sample_timesteps_per_iteration
        )
        self._record_episodes(episodes)
        self.replay.add_episodes(episodes)
        if len(self.replay) < cfg.num_steps_sampled_before_learning_starts:
            return {"buffer_size": float(len(self.replay))}
        metrics_list = []
        assert self.learner_group.is_local, (
            "DQN uses a local learner (replay lives with the algorithm)"
        )
        learner: DQNLearner = self.learner_group._local
        for _ in range(cfg.updates_per_iteration):
            batch = self.replay.sample(cfg.train_batch_size)
            idx = batch.pop("batch_indexes")
            m = learner.update({k: v for k, v in batch.items()})
            if cfg.prioritized_replay:
                self.replay.update_priorities(
                    idx, learner.td_errors(batch)
                )
            metrics_list.append(m)
        self.env_runner_group.sync_weights(self.learner_group.get_weights())
        out = {
            k: float(np.mean([m[k] for m in metrics_list]))
            for k in metrics_list[0]
        }
        out["buffer_size"] = float(len(self.replay))
        return out
