"""Tuned-example regression configs: declarative pass/fail bars.

Reference: rllib/tuned_examples/ — per-algorithm YAML configs with a
``stop`` block that doubles as the CI pass criterion ("this algorithm
must reach return X on env Y within budget Z"). The runner here loads
a config, builds the algorithm through the same public config API a
user would, trains until the stop criteria are met (pass) or the
budget runs out (fail), and reports the trajectory — so every algo's
learning behavior is pinned by data, not by hand-written test code.

Config schema (YAML)::

    algorithm: PPO                # class name in rllib.algorithms
    env: CartPole-v1
    stop:
      episode_return_mean: 400.0  # pass when reached
    max_iterations: 40            # fail if not reached by then
    config:                       # AlgorithmConfig section calls
      env_runners: {num_env_runners: 0, num_envs_per_env_runner: 8}
      training: {lr: 0.0003, train_batch_size: 2000}
      debugging: {seed: 0}
"""
from __future__ import annotations

import glob
import importlib
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

_ALGO_MODULES = {
    "PPO": "ppo",
    "DQN": "dqn",
    "IMPALA": "impala",
    "APPO": "appo",
    "SAC": "sac",
    "CQL": "cql",
    "MARWIL": "marwil",
    "BC": "marwil",
    "DreamerV3": "dreamerv3",
}

EXAMPLES_DIR = os.path.dirname(__file__)


def list_examples() -> List[str]:
    return sorted(glob.glob(os.path.join(EXAMPLES_DIR, "*.yaml")))


@dataclass
class RegressionResult:
    name: str
    passed: bool
    iterations: int
    best: Dict[str, float]
    history: List[Dict[str, float]] = field(default_factory=list)


def _config_for(spec: Dict[str, Any]):
    algo_name = spec["algorithm"]
    mod = importlib.import_module(
        f"..algorithms.{_ALGO_MODULES[algo_name]}", __name__
    )
    cfg = getattr(mod, f"{algo_name}Config")()
    cfg.environment(spec["env"])
    for section, kwargs in (spec.get("config") or {}).items():
        getattr(cfg, section)(**kwargs)
    return cfg


def _metric_value(result: Dict[str, Any], dotted: str) -> Optional[float]:
    cur: Any = result
    for part in dotted.split("/"):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    try:
        return float(cur)
    except (TypeError, ValueError):
        return None


def run_regression(path: str) -> RegressionResult:
    """Train one example to its stop criteria; pass/fail by the bar."""
    import numpy as np
    import yaml

    with open(path) as f:
        spec = yaml.safe_load(f)
    stop: Dict[str, float] = spec["stop"]
    max_iters = int(spec.get("max_iterations", 100))
    algo = _config_for(spec).build()
    history: List[Dict[str, float]] = []
    best: Dict[str, float] = {}
    passed = False
    it = 0
    try:
        for it in range(1, max_iters + 1):
            result = algo.train()
            snap = {}
            for metric in stop:
                v = _metric_value(result, metric)
                if v is not None and np.isfinite(v):
                    snap[metric] = v
                    best[metric] = max(best.get(metric, -np.inf), v)
            history.append(snap)
            if stop and all(
                best.get(m, -np.inf) >= bar for m, bar in stop.items()
            ):
                passed = True
                break
    finally:
        algo.stop()
    return RegressionResult(
        name=os.path.basename(path),
        passed=passed,
        iterations=it,
        best=best,
        history=history,
    )
