from .io import OfflineData, SampleReader, SampleWriter, episodes_to_rows, rows_to_episodes

__all__ = [
    "OfflineData",
    "SampleReader",
    "SampleWriter",
    "episodes_to_rows",
    "rows_to_episodes",
]
