"""Offline sample IO: record rollouts, read them back for offline RL.

Reference: rllib/offline/ — OfflineData wraps ray.data to read
experience datasets (offline_data.py), output writers record rollouts
as JSON episodes (json_writer.py / offline_env_runner.py). Same design
here: episodes serialize to plain-JSON rows (one row per episode, lists
for arrays) and the reader rides ray_tpu.data, so offline training
inherits the Data library's parallel reads, shuffles, and streaming.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

from ..env.episode import SingleAgentEpisode


def episodes_to_rows(episodes: List[SingleAgentEpisode]) -> List[Dict[str, Any]]:
    rows = []
    for ep in episodes:
        ep = ep.finalize()
        row = {
            "observations": np.asarray(ep.observations).tolist(),
            "actions": np.asarray(ep.actions).tolist(),
            "rewards": np.asarray(ep.rewards).tolist(),
            "terminated": bool(ep.is_terminated),
            "truncated": bool(ep.is_truncated),
        }
        for k, v in ep.extra_model_outputs.items():
            row[f"extra__{k}"] = np.asarray(v).tolist()
        rows.append(row)
    return rows


def rows_to_episodes(rows: List[Dict[str, Any]]) -> List[SingleAgentEpisode]:
    eps = []
    for row in rows:
        obs = np.asarray(row["observations"], np.float32)
        ep = SingleAgentEpisode(initial_observation=obs[0])
        actions = row["actions"]
        rewards = row["rewards"]
        extras = {
            k[len("extra__"):]: row[k] for k in row if k.startswith("extra__")
        }
        n = len(actions)
        for t in range(n):
            ep.add_env_step(
                obs[t + 1],
                np.asarray(actions[t]),
                float(rewards[t]),
                terminated=bool(row["terminated"]) and t == n - 1,
                truncated=bool(row["truncated"]) and t == n - 1,
                extra_model_outputs={
                    k: np.asarray(v[t]) for k, v in extras.items()
                },
            )
        eps.append(ep.finalize())
    return eps


class SampleWriter:
    """Append-only JSONL episode writer (reference: JsonWriter). Rolls
    to a new file every ``max_file_size`` bytes."""

    def __init__(self, path: str, max_file_size: int = 64 * 1024 * 1024):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self.max_file_size = max_file_size
        self._f = None
        self._written = 0

    def _open(self):
        name = f"samples-{int(time.time() * 1000):x}-{os.getpid()}.jsonl"
        self._f = open(os.path.join(self.path, name), "w")
        self._written = 0

    def write(self, episodes: List[SingleAgentEpisode]) -> None:
        if self._f is None or self._written > self.max_file_size:
            if self._f:
                self._f.close()
            self._open()
        for row in episodes_to_rows(episodes):
            line = json.dumps(row)
            self._f.write(line + "\n")
            self._written += len(line) + 1
        self._f.flush()

    def close(self):
        if self._f:
            self._f.close()
            self._f = None


class SampleReader:
    """Reads a JSONL sample dir directly (no cluster needed)."""

    def __init__(self, path: str, shuffle: bool = True,
                 seed: Optional[int] = None):
        self.files = sorted(
            os.path.join(path, f)
            for f in os.listdir(path)
            if f.endswith(".jsonl")
        )
        if not self.files:
            raise FileNotFoundError(f"no .jsonl sample files under {path}")
        self._rng = np.random.default_rng(seed)
        self.shuffle = shuffle

    def read_all(self) -> List[SingleAgentEpisode]:
        rows = []
        for f in self.files:
            with open(f) as fh:
                rows.extend(json.loads(l) for l in fh if l.strip())
        return rows_to_episodes(rows)

    def iter_episodes(self, batch_size: int) -> Iterator[List[SingleAgentEpisode]]:
        """Infinite iterator of episode minibatches."""
        eps = self.read_all()
        while True:
            order = (
                self._rng.permutation(len(eps))
                if self.shuffle
                else np.arange(len(eps))
            )
            batch: List[SingleAgentEpisode] = []
            steps = 0
            for i in order:
                batch.append(eps[i])
                steps += len(eps[i])
                if steps >= batch_size:
                    yield batch
                    batch, steps = [], 0


class OfflineData:
    """ray_tpu.data-backed offline dataset (reference:
    rllib/offline/offline_data.py — wraps ray.data.read_json). Episodes
    stream through the Data library's parallel block reads; requires a
    running cluster."""

    def __init__(self, paths, *, parallelism: int = -1):
        import ray_tpu.data as rdata

        if isinstance(paths, str) and os.path.isdir(paths):
            paths = [
                os.path.join(paths, f)
                for f in sorted(os.listdir(paths))
                if f.endswith(".jsonl") or f.endswith(".json")
            ]
        self.dataset = rdata.read_json(paths, parallelism=parallelism)

    def iter_episode_batches(
        self, *, batch_size: int
    ) -> Iterator[List[SingleAgentEpisode]]:
        """One pass over the dataset in episode minibatches of at least
        ``batch_size`` env steps."""
        batch: List[SingleAgentEpisode] = []
        steps = 0
        for row in self.dataset.iter_rows():
            (ep,) = rows_to_episodes([row])
            batch.append(ep)
            steps += len(ep)
            if steps >= batch_size:
                yield batch
                batch, steps = [], 0
        if batch:
            yield batch
