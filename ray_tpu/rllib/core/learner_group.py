"""LearnerGroup: local learner or a gang of learner actors.

Reference: rllib/core/learner/learner_group.py:74. num_learners=0 runs
the learner in-process (the common TPU case: one process, all local
chips in one mesh — DP compiles in-graph). num_learners=N spawns N
actors gang-placed via a STRICT_SPREAD-less PG and wires an
out-of-graph collective group for gradient averaging (the multi-host
DCN path, reference's DDP equivalent).
"""
from __future__ import annotations

import pickle
import uuid
from typing import Any, Dict, List, Optional

import ray_tpu

from .learner import Learner, LearnerActor


class LearnerGroup:
    def __init__(self, *, learner_cls, module_spec, config: Dict[str, Any]):
        self._config = dict(config)
        n = config.get("num_learners", 0)
        self._local: Optional[Learner] = None
        self._actors: List[Any] = []
        if n == 0:
            self._local = learner_cls(module_spec=module_spec, config=config)
            self._local.build()
        else:
            blobs = (
                pickle.dumps(learner_cls),
                pickle.dumps(module_spec),
                pickle.dumps(config),
            )
            actor_cls = ray_tpu.remote(LearnerActor).options(
                num_cpus=config.get("num_cpus_per_learner", 1),
                num_tpus=config.get("num_tpus_per_learner", 0) or None,
            )
            self._actors = [actor_cls.remote(*blobs) for _ in range(n)]
            if n > 1:
                group = f"learners-{uuid.uuid4().hex[:6]}"
                ray_tpu.get(
                    [
                        a.setup_collective.remote(group, n, rank)
                        for rank, a in enumerate(self._actors)
                    ]
                )
            # Align initial weights (each actor seeded identically, but
            # make it explicit).
            if n > 1:
                w = ray_tpu.get(self._actors[0].get_weights.remote())
                ref = ray_tpu.put(w)
                ray_tpu.get([a.set_weights.remote(ref) for a in self._actors[1:]])

    @property
    def is_local(self) -> bool:
        return self._local is not None

    # ------------------------------------------------------------ update
    def update_from_episodes(self, episodes) -> Dict[str, Any]:
        if self._local is not None:
            batch = self._local.build_batch(episodes)  # type: ignore[attr-defined]
            return self._local.update(batch)
        n = len(self._actors)
        shards = [episodes[i::n] for i in range(n)]
        refs = [
            a.update_from_episodes.remote(shard)
            for a, shard in zip(self._actors, shards)
            if shard
        ]
        results = ray_tpu.get(refs)
        return _mean_metrics(results)

    def update_from_batch(self, batch) -> Dict[str, Any]:
        if self._local is not None:
            return self._local.update(batch)
        n = len(self._actors)
        size = len(next(iter(batch.values())))
        per = max(1, size // n)
        refs = []
        for i, a in enumerate(self._actors):
            lo, hi = i * per, (i + 1) * per if i < n - 1 else size
            if lo >= size:
                break
            refs.append(a.update.remote({k: v[lo:hi] for k, v in batch.items()}))
        return _mean_metrics(ray_tpu.get(refs))

    # ----------------------------------------------------------- weights
    def get_weights(self):
        if self._local is not None:
            return self._local.get_weights()
        return ray_tpu.get(self._actors[0].get_weights.remote())

    def set_weights(self, weights):
        if self._local is not None:
            self._local.set_weights(weights)
        else:
            ref = ray_tpu.put(weights)
            ray_tpu.get([a.set_weights.remote(ref) for a in self._actors])

    def get_state(self):
        if self._local is not None:
            return self._local.get_state()
        return ray_tpu.get(self._actors[0].get_state.remote())

    def set_state(self, state):
        if self._local is not None:
            self._local.set_state(state)
        else:
            ref = ray_tpu.put(state)
            ray_tpu.get([a.set_state.remote(ref) for a in self._actors])

    def shutdown(self):
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:  # noqa: BLE001
                pass
        self._actors = []


def _mean_metrics(results: List[Dict[str, Any]]) -> Dict[str, Any]:
    import numpy as np

    if not results:
        return {}
    return {
        k: float(np.mean([r[k] for r in results if k in r]))
        for k in results[0]
    }
