"""Learner: owns params + optimizer, runs the jitted update.

Reference: rllib/core/learner/learner.py + torch_learner.py:56. The
TPU-first inversion: instead of torch DDP across learner processes, the
whole gradient step is ONE jitted jax program; data parallelism over
local chips compiles into the same program via a `data`-axis mesh
(XLA inserts the gradient psum over ICI). Multi-process learners (one
per TPU host) still work by out-of-graph gradient allreduce through
ray_tpu.util.collective — that's the DCN path, used only when a single
mesh can't span the learners.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np


class Learner:
    """Subclasses implement ``compute_loss(params, batch, rng)``."""

    def __init__(self, *, module_spec, config: Dict[str, Any]):
        self._module_spec = module_spec
        self.config = dict(config)
        self.module = None
        self.params = None
        self.opt_state = None
        self._tx = None
        self._jit_update = None
        self._rng = None
        self._collective_group: Optional[str] = None
        self._mesh = None

    # ------------------------------------------------------------- build
    def build(self) -> None:
        import jax
        import optax

        self.module = self._module_spec.build()
        seed = int(self.config.get("seed") or 0)
        self._rng = jax.random.PRNGKey(seed)
        self._rng, init_rng = jax.random.split(self._rng)
        if self.config.get("num_devices_per_learner", 1) > 1:
            from ...parallel import MeshSpec

            n = self.config["num_devices_per_learner"]
            self._mesh = MeshSpec(data=n).build()
        self.params = self.module.init_params(init_rng)
        self._np_rng = np.random.default_rng(seed)
        lr = self.config.get("lr", 3e-4)
        clip = self.config.get("grad_clip")
        chain = []
        if clip:
            chain.append(optax.clip_by_global_norm(clip))
        chain.append(optax.adam(lr))
        self._tx = optax.chain(*chain)
        self.opt_state = self._tx.init(self.params)

    # -------------------------------------------------------------- loss
    def compute_loss(
        self, params, batch: Dict[str, Any], rng
    ) -> Tuple[Any, Dict[str, Any]]:
        raise NotImplementedError

    # ------------------------------------------------------------ update
    def _make_update_fn(self):
        import jax

        tx = self._tx

        def update_step(params, opt_state, batch, rng):
            def loss_fn(p):
                return self.compute_loss(p, batch, rng)

            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            import optax

            params = optax.apply_updates(params, updates)
            metrics["total_loss"] = loss
            return params, opt_state, metrics

        return update_step

    def _ensure_jit(self):
        import jax

        if self._jit_update is None:
            fn = self._make_update_fn()
            if self._collective_group:
                fn = self._wrap_collective(fn)
                self._jit_update = fn  # allreduce is out-of-graph
            else:
                self._jit_update = jax.jit(fn, donate_argnums=(0, 1))

    def _wrap_collective(self, update_fn):
        """Out-of-graph gradient averaging across learner processes
        (DCN path). Gradients are computed jitted, allreduced via the
        collective API, then applied jitted."""
        import jax
        import optax

        group = self._collective_group
        tx = self._tx

        @jax.jit
        def grads_fn(params, batch, rng):
            def loss_fn(p):
                return self.compute_loss(p, batch, rng)

            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params)
            metrics["total_loss"] = loss
            return grads, metrics

        @jax.jit
        def apply_fn(params, opt_state, grads):
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state

        def stepped(params, opt_state, batch, rng):
            from ...util import collective

            grads, metrics = grads_fn(params, batch, rng)
            flat, tree = jax.tree_util.tree_flatten(grads)
            reduced = [
                collective.allreduce(np.asarray(g), group_name=group, op="mean")
                for g in flat
            ]
            grads = jax.tree_util.tree_unflatten(tree, reduced)
            params, opt_state = apply_fn(params, opt_state, grads)
            return params, opt_state, metrics

        return stepped

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        """Run minibatch SGD over the batch; returns averaged metrics."""
        import jax
        import jax.numpy as jnp

        self._ensure_jit()
        minibatch = self.config.get("minibatch_size")
        epochs = self.config.get("num_epochs", 1)
        n = len(
            next(v for v in batch.values() if not isinstance(v, dict))
        )
        all_metrics: List[Dict[str, Any]] = []
        for _ in range(epochs):
            if minibatch and minibatch < n:
                perm = self._np_rng.permutation(n)
                # Truncate to full minibatches: static shapes keep XLA
                # from recompiling per ragged tail.
                num_mb = n // minibatch
                idxs = [
                    perm[i * minibatch : (i + 1) * minibatch]
                    for i in range(num_mb)
                ]
            else:
                idxs = [None]
            for idx in idxs:
                mb = (
                    batch
                    if idx is None
                    else {
                        k: (v[idx] if not isinstance(v, dict) else v)
                        for k, v in batch.items()
                    }
                )
                # dict-valued entries are param pytrees (e.g. a target
                # network) riding along as jit args — pass through.
                mb = {
                    k: (jnp.asarray(v) if not isinstance(v, dict) else v)
                    for k, v in mb.items()
                }
                self._rng, step_rng = jax.random.split(self._rng)
                self.params, self.opt_state, metrics = self._jit_update(
                    self.params, self.opt_state, mb, step_rng
                )
                all_metrics.append(metrics)
        out = {
            k: float(np.mean([jax.device_get(m[k]) for m in all_metrics]))
            for k in all_metrics[0]
        }
        return out

    # ----------------------------------------------------------- weights
    def get_weights(self):
        import jax

        return jax.device_get(self.params)

    def set_weights(self, weights) -> None:
        self.params = weights

    def get_state(self) -> Dict[str, Any]:
        import jax

        return {
            "params": jax.device_get(self.params),
            "opt_state": jax.device_get(self.opt_state),
        }

    def set_state(self, state: Dict[str, Any]) -> None:
        self.params = state["params"]
        self.opt_state = state["opt_state"]

    # ------------------------------------------------- collective (DCN)
    def setup_collective(self, group_name: str, world_size: int, rank: int):
        from ...util import collective

        collective.init_collective_group(
            world_size=world_size, rank=rank, group_name=group_name
        )
        self._collective_group = group_name
        self._jit_update = None


class LearnerActor:
    """Hosts a Learner in a worker process (possibly bound to TPU
    chips); thin RPC surface for LearnerGroup."""

    def __init__(self, learner_cls_blob: bytes, module_spec_blob: bytes,
                 config_blob: bytes):
        import pickle

        learner_cls = pickle.loads(learner_cls_blob)
        self._learner: Learner = learner_cls(
            module_spec=pickle.loads(module_spec_blob),
            config=pickle.loads(config_blob),
        )
        self._learner.build()

    def setup_collective(self, group_name: str, world_size: int, rank: int):
        self._learner.setup_collective(group_name, world_size, rank)
        return rank

    def update_from_episodes(self, episodes):
        batch = self._learner.build_batch(episodes)  # type: ignore[attr-defined]
        return self._learner.update(batch)

    def update(self, batch):
        return self._learner.update(batch)

    def get_weights(self):
        return self._learner.get_weights()

    def set_weights(self, weights):
        self._learner.set_weights(weights)

    def get_state(self):
        return self._learner.get_state()

    def set_state(self, state):
        self._learner.set_state(state)

    def call(self, method: str, *args, **kwargs):
        return getattr(self._learner, method)(*args, **kwargs)
