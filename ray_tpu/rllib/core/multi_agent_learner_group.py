"""MultiAgentLearnerGroup: one LearnerGroup per policy module.

Reference: the reference trains a single MultiRLModule inside one
learner (multi_rl_module.py + learner.py MultiAgentBatch path). Here
each module gets its own (possibly remote) LearnerGroup and episodes
route by their ``module_id`` tag — simpler, and the per-module update
is still one jitted program each. The facade mirrors LearnerGroup's
surface so Algorithm.training_step code is agnostic to single- vs
multi-agent.
"""
from __future__ import annotations

from typing import Any, Dict, List

from .learner_group import LearnerGroup
from .multi_rl_module import MultiRLModuleSpec


class MultiAgentLearnerGroup:
    def __init__(
        self, *, learner_cls, module_spec: MultiRLModuleSpec, config
    ):
        self._groups: Dict[str, LearnerGroup] = {
            mid: LearnerGroup(
                learner_cls=learner_cls, module_spec=spec, config=config
            )
            for mid, spec in module_spec.module_specs.items()
        }

    @property
    def is_local(self) -> bool:
        return all(g.is_local for g in self._groups.values())

    def update_from_episodes(self, episodes: List) -> Dict[str, Any]:
        by_module: Dict[str, List] = {}
        for ep in episodes:
            mid = getattr(ep, "module_id", None)
            if mid is None:
                raise ValueError(
                    "episode missing module_id tag — multi-agent episodes "
                    "must come from MultiAgentEnvRunner"
                )
            by_module.setdefault(mid, []).append(ep)
        out: Dict[str, Any] = {}
        for mid, eps in by_module.items():
            for k, v in self._groups[mid].update_from_episodes(eps).items():
                out[f"{mid}/{k}"] = v
        return out

    def get_weights(self) -> Dict[str, Any]:
        return {mid: g.get_weights() for mid, g in self._groups.items()}

    def set_weights(self, weights: Dict[str, Any]) -> None:
        for mid, w in weights.items():
            self._groups[mid].set_weights(w)

    def get_state(self) -> Dict[str, Any]:
        return {mid: g.get_state() for mid, g in self._groups.items()}

    def set_state(self, state: Dict[str, Any]) -> None:
        for mid, s in state.items():
            if mid in self._groups:
                self._groups[mid].set_state(s)

    def shutdown(self) -> None:
        for g in self._groups.values():
            g.shutdown()
