"""RLModule: the neural-network abstraction of the new API stack.

Reference: rllib/core/rl_module/rl_module.py — a framework-native module
with three forward passes (inference / exploration / train). Here the
module is *functional* (flax): parameters live outside the module and
every forward is a pure ``apply(params, batch)`` so the learner can jit
the whole update and env runners can run the same apply on CPU numpy
weights. This is the TPU-first inversion of the reference's stateful
torch modules.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np


class Columns:
    """Batch column names (reference: rllib/core/columns.py)."""

    OBS = "obs"
    ACTIONS = "actions"
    REWARDS = "rewards"
    NEXT_OBS = "next_obs"
    TERMINATEDS = "terminateds"
    TRUNCATEDS = "truncateds"
    ACTION_LOGP = "action_logp"
    ACTION_DIST_INPUTS = "action_dist_inputs"
    VF_PREDS = "vf_preds"
    ADVANTAGES = "advantages"
    VALUE_TARGETS = "value_targets"
    LOSS_MASK = "loss_mask"


class RLModule:
    """Subclass and implement ``setup`` + the forward methods.

    All forwards are pure functions of (params, batch) returning a dict
    of outputs; ``init_params(rng)`` builds fresh parameters.
    """

    def __init__(self, observation_space, action_space, model_config: dict):
        self.observation_space = observation_space
        self.action_space = action_space
        self.model_config = dict(model_config or {})
        self.setup()

    # ------------------------------------------------------------- hooks
    def setup(self) -> None:  # pragma: no cover - default no-op
        pass

    def init_params(self, rng) -> Any:
        raise NotImplementedError

    def forward_inference(self, params, batch: Dict[str, Any]) -> Dict[str, Any]:
        """Greedy/deterministic forward for evaluation & serving."""
        return self.forward_exploration(params, batch)

    def forward_exploration(self, params, batch: Dict[str, Any]) -> Dict[str, Any]:
        """Stochastic forward used for sample collection."""
        raise NotImplementedError

    def forward_train(self, params, batch: Dict[str, Any]) -> Dict[str, Any]:
        """Forward used inside the loss (jitted by the learner)."""
        raise NotImplementedError

    # --------------------------------------------------------- utilities
    def input_dim(self) -> int:
        space = self.observation_space
        return int(np.prod(space.shape))

    def num_actions(self) -> int:
        import gymnasium as gym

        if isinstance(self.action_space, gym.spaces.Discrete):
            return int(self.action_space.n)
        return int(np.prod(self.action_space.shape))


@dataclass
class RLModuleSpec:
    """Builds an RLModule from spaces + config (reference:
    rllib/core/rl_module/rl_module.py RLModuleSpec)."""

    module_class: Optional[type] = None
    observation_space: Any = None
    action_space: Any = None
    model_config: Dict[str, Any] = field(default_factory=dict)

    def build(self) -> RLModule:
        if self.module_class is None:
            raise ValueError("RLModuleSpec.module_class not set")
        return self.module_class(
            self.observation_space, self.action_space, self.model_config
        )


# --------------------------------------------------------------- flax MLPs
def _mlp(hidden: Sequence[int], out: int, out_scale: float = 0.01):
    import flax.linen as nn

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = x.reshape((x.shape[0], -1))
            for h in hidden:
                x = nn.tanh(nn.Dense(h)(x))
            # Small-scale head init: near-uniform initial policy and
            # near-zero initial values — bootstrapped targets (V-trace,
            # TD) start unbiased instead of propagating init noise.
            return nn.Dense(
                out,
                kernel_init=nn.initializers.variance_scaling(
                    out_scale, "fan_in", "truncated_normal"
                ),
            )(x)

    return MLP()


class DiscretePolicyModule(RLModule):
    """Categorical policy + value head over an MLP trunk — the default
    module for discrete-action envs (reference: rllib default
    PPO/IMPALA catalog MLP models)."""

    def setup(self) -> None:
        hidden = tuple(self.model_config.get("fcnet_hiddens", (64, 64)))
        self._pi = _mlp(hidden, self.num_actions())
        self._vf = _mlp(hidden, 1)

    def init_params(self, rng):
        import jax
        import jax.numpy as jnp

        dummy = jnp.zeros((1, self.input_dim()), jnp.float32)
        k1, k2 = jax.random.split(rng)
        return {
            "pi": self._pi.init(k1, dummy),
            "vf": self._vf.init(k2, dummy),
        }

    def forward_exploration(self, params, batch):
        logits = self._pi.apply(params["pi"], batch[Columns.OBS])
        return {Columns.ACTION_DIST_INPUTS: logits}

    def forward_train(self, params, batch):
        obs = batch[Columns.OBS]
        logits = self._pi.apply(params["pi"], obs)
        vf = self._vf.apply(params["vf"], obs)[..., 0]
        return {Columns.ACTION_DIST_INPUTS: logits, Columns.VF_PREDS: vf}

    def compute_values(self, params, obs):
        return self._vf.apply(params["vf"], obs)[..., 0]


class QNetworkModule(RLModule):
    """Q-network (+ target handled by the learner) for DQN."""

    def setup(self) -> None:
        hidden = tuple(self.model_config.get("fcnet_hiddens", (64, 64)))
        self._q = _mlp(hidden, self.num_actions())

    def init_params(self, rng):
        import jax.numpy as jnp

        dummy = jnp.zeros((1, self.input_dim()), jnp.float32)
        return {"q": self._q.init(rng, dummy)}

    def forward_exploration(self, params, batch):
        q = self._q.apply(params["q"], batch[Columns.OBS])
        return {"q_values": q, Columns.ACTION_DIST_INPUTS: q}

    def forward_train(self, params, batch):
        return {"q_values": self._q.apply(params["q"], batch[Columns.OBS])}
