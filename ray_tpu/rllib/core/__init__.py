DEFAULT_MODULE_ID = "default_policy"
