"""MultiRLModule: a dict of RLModules keyed by module id.

Reference: rllib/core/rl_module/multi_rl_module.py — the container the
multi-agent stack trains; each policy ("module") has its own params and
forward. Params here are a plain dict {module_id: pytree}, so the
learner side can update each module independently and weight sync ships
one dict.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict

from .rl_module import RLModule, RLModuleSpec


@dataclass
class MultiRLModuleSpec:
    module_specs: Dict[str, RLModuleSpec] = field(default_factory=dict)

    def build(self) -> "MultiRLModule":
        return MultiRLModule(
            {mid: spec.build() for mid, spec in self.module_specs.items()}
        )


class MultiRLModule:
    def __init__(self, modules: Dict[str, RLModule]):
        self._modules = dict(modules)

    def __getitem__(self, module_id: str) -> RLModule:
        return self._modules[module_id]

    def __contains__(self, module_id: str) -> bool:
        return module_id in self._modules

    def keys(self):
        return self._modules.keys()

    def items(self):
        return self._modules.items()

    def init_params(self, rng) -> Dict[str, Any]:
        import jax

        keys = jax.random.split(rng, len(self._modules))
        return {
            mid: m.init_params(k)
            for (mid, m), k in zip(sorted(self._modules.items()), keys)
        }
