"""ray_tpu.rllib: reinforcement learning on the actor substrate.

Architecture mirrors the reference's new API stack (rllib/ — SURVEY.md
§2.4): `EnvRunnerGroup` of CPU actors sampling gymnasium vector envs,
connector pipelines between env and module, flax `RLModule`s replacing
torch ModelV2/Policy, and a `Learner`/`LearnerGroup` whose update is a
single jitted jax program — on TPU the gradient step (and any
data-parallel mean) compiles into one XLA program over the device mesh
instead of DDP/NCCL.

    from ray_tpu.rllib.algorithms.ppo import PPOConfig

    algo = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2)
        .build()
    )
    for _ in range(10):
        result = algo.train()
"""
from __future__ import annotations

from .core.multi_rl_module import MultiRLModule, MultiRLModuleSpec  # noqa: F401
from .core.rl_module import RLModule, RLModuleSpec  # noqa: F401
from .env.episode import SingleAgentEpisode  # noqa: F401
from .env.multi_agent_env import MultiAgentEnv, make_multi_agent  # noqa: F401

__all__ = [
    "MultiAgentEnv",
    "MultiRLModule",
    "MultiRLModuleSpec",
    "RLModule",
    "RLModuleSpec",
    "SingleAgentEpisode",
    "make_multi_agent",
]

from ray_tpu._private import usage_stats as _usage

_usage.record_library_usage("rllib")
