"""Autoscaler v2: instance manager + reconciler over a cloud provider.

Reference: python/ray/autoscaler/v2/ — the instance manager owns a
per-instance lifecycle state machine
(instance_manager/common.py InstanceUtil):

    QUEUED -> REQUESTED -> ALLOCATED -> RAY_RUNNING -> RAY_STOPPED
                      \\-> ALLOCATION_FAILED (retry)   -> TERMINATING
                                                       -> TERMINATED

and the Reconciler (instance_manager/reconciler.py) drives it by
diffing three views every tick: the CLOUD view (provider instances),
the RAY view (GCS nodes), and DEMAND (unplaceable shapes). Scale-down
is graceful: idle nodes are DRAINED (no new placements, running work
finishes) before their instance is released.

The ProcessCloudProvider launches REAL node daemons
(`ray_tpu._private.raylet` subprocesses over the TCP control plane) —
the same daemon a GCE/TPU-pod provider would start on a fresh VM — so
the whole loop is testable end-to-end on one box. A real cloud
provider implements the same 3-method surface against its VM API.
"""
from __future__ import annotations

import json
import subprocess
import sys
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .._private.gcs import _fits

# Lifecycle states (reference: instance_manager/common.py).
QUEUED = "QUEUED"
REQUESTED = "REQUESTED"
ALLOCATED = "ALLOCATED"
RAY_RUNNING = "RAY_RUNNING"
RAY_STOPPED = "RAY_STOPPED"
TERMINATING = "TERMINATING"
TERMINATED = "TERMINATED"
ALLOCATION_FAILED = "ALLOCATION_FAILED"

_TRANSITIONS = {
    QUEUED: {REQUESTED},
    REQUESTED: {ALLOCATED, ALLOCATION_FAILED},
    ALLOCATED: {RAY_RUNNING, RAY_STOPPED, TERMINATING},
    RAY_RUNNING: {RAY_STOPPED, TERMINATING},
    RAY_STOPPED: {TERMINATING},
    TERMINATING: {TERMINATED},
    ALLOCATION_FAILED: {QUEUED, TERMINATED},
    TERMINATED: set(),
}


@dataclass
class Instance:
    instance_id: str
    node_type: str
    resources: Dict[str, float]
    hosts: int = 1  # >1: an atomic multi-host slice (all-or-nothing)
    status: str = QUEUED
    cloud_instance_id: Optional[str] = None
    node_id: Optional[bytes] = None  # GCS node id once RAY_RUNNING
    launch_attempts: int = 0
    status_since: float = field(default_factory=time.monotonic)
    history: List[str] = field(default_factory=list)


class InstanceManager:
    """Owns instance records; every transition is validated and logged
    (reference: instance_manager/instance_manager.py)."""

    def __init__(self):
        self._instances: Dict[str, Instance] = {}

    def create(self, node_type: str, resources: Dict[str, float],
               hosts: int = 1) -> Instance:
        inst = Instance(
            instance_id=uuid.uuid4().hex[:12],
            node_type=node_type,
            resources=dict(resources),
            hosts=hosts,
        )
        inst.history.append(QUEUED)
        self._instances[inst.instance_id] = inst
        return inst

    def transition(self, inst: Instance, new_status: str) -> None:
        if new_status not in _TRANSITIONS.get(inst.status, set()):
            raise ValueError(
                f"invalid transition {inst.status} -> {new_status} "
                f"for instance {inst.instance_id}"
            )
        inst.status = new_status
        inst.status_since = time.monotonic()
        inst.history.append(new_status)

    def instances(self, *statuses: str) -> List[Instance]:
        if not statuses:
            return list(self._instances.values())
        return [i for i in self._instances.values() if i.status in statuses]

    def get(self, instance_id: str) -> Optional[Instance]:
        return self._instances.get(instance_id)


class CloudProvider:
    """3-method provider surface (reference:
    instance_manager/cloud_providers/cloud_provider.py)."""

    def launch(self, instance: Instance) -> str:
        """Start a VM/process for the instance; returns cloud id.
        May raise — the reconciler retries with backoff."""
        raise NotImplementedError

    def terminate(self, cloud_instance_id: str) -> None:
        raise NotImplementedError

    def running_instances(self) -> Dict[str, Any]:
        """cloud_instance_id -> opaque metadata for live instances."""
        raise NotImplementedError


class ProcessCloudProvider(CloudProvider):
    """Each 'instance' is a real node-daemon subprocess joining the
    head over TCP — the exact process a cloud VM's startup script would
    run (`ray_tpu start --address=<head>`)."""

    def __init__(self, head_address: str, authkey: bytes):
        self.head_address = head_address
        self.authkey = authkey
        self._procs: Dict[str, subprocess.Popen] = {}

    def launch(self, instance: Instance) -> str:
        proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "ray_tpu._private.raylet",
                "--address",
                self.head_address,
                "--authkey",
                self.authkey.hex(),
                "--resources",
                json.dumps(instance.resources),
                "--label",
                f"v2:{instance.instance_id}",
                "--transfer-host",
                "127.0.0.1",
            ],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        cloud_id = f"proc-{proc.pid}"
        self._procs[cloud_id] = proc
        return cloud_id

    def terminate(self, cloud_instance_id: str) -> None:
        proc = self._procs.pop(cloud_instance_id, None)
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()

    def running_instances(self) -> Dict[str, Any]:
        return {
            cid: {"pid": p.pid}
            for cid, p in self._procs.items()
            if p.poll() is None
        }


class Reconciler:
    """One step() = one reconciliation pass over cloud/ray/demand views
    (reference: instance_manager/reconciler.py Reconciler.reconcile)."""

    def __init__(
        self,
        node_types: Dict[str, Dict[str, Any]],
        provider: CloudProvider,
        *,
        idle_timeout_s: float = 30.0,
        request_timeout_s: float = 60.0,
        max_launch_attempts: int = 3,
        drain_deadline_s: float = 30.0,
    ):
        self.node_types = node_types
        self.provider = provider
        self.im = InstanceManager()
        self.idle_timeout_s = idle_timeout_s
        self.request_timeout_s = request_timeout_s
        self.max_launch_attempts = max_launch_attempts
        self.drain_deadline_s = drain_deadline_s
        self._idle_since: Dict[str, float] = {}  # instance_id -> t
        self._draining: set = set()

    # ------------------------------------------------------------- views
    def _client(self):
        from .._private.worker import global_client

        return global_client()


    # -------------------------------------------------------------- step
    def step(self) -> None:
        now = time.monotonic()
        cloud = self.provider.running_instances()
        info = self._client().cluster_info()
        # instance_id -> [host nodes]; single-host instances are
        # labeled "v2:<iid>", slice hosts "v2:<iid>:h<k>".
        ray_view: Dict[str, List[Dict]] = {}
        for n in info["nodes"]:
            label = n.get("label", "")
            if label.startswith("v2:"):
                iid = label[3:].split(":", 1)[0]
                ray_view.setdefault(iid, []).append(n)
        reply = self._client().request({"type": "get_pending_demand"})
        self._sync_cloud(cloud, now)
        self._sync_ray(ray_view, cloud)
        self._scale_up(reply, info["nodes"])
        self._scale_down(reply, ray_view, now)

    # ------------------------------------------------------ cloud sync
    def _sync_cloud(self, cloud: Dict[str, Any], now: float) -> None:
        for inst in self.im.instances(REQUESTED):
            if inst.cloud_instance_id in cloud:
                self.im.transition(inst, ALLOCATED)
            elif now - inst.status_since > self.request_timeout_s:
                self.im.transition(inst, ALLOCATION_FAILED)
        for inst in self.im.instances(ALLOCATION_FAILED):
            if inst.launch_attempts < self.max_launch_attempts:
                self.im.transition(inst, QUEUED)
            else:
                self.im.transition(inst, TERMINATED)
        # Cloud instance vanished under a live record (preempted VM,
        # crashed daemon): mark stopped so it gets cleaned up.
        for inst in self.im.instances(ALLOCATED, RAY_RUNNING):
            if inst.cloud_instance_id not in cloud:
                self.im.transition(inst, RAY_STOPPED)
        # Allocated but never (fully) joined — e.g. one slice host died
        # before registering: the survivors pin phantom capacity and
        # the demand they were launched for can never place. Recycle.
        for inst in self.im.instances(ALLOCATED):
            if now - inst.status_since > self.request_timeout_s:
                self.im.transition(inst, RAY_STOPPED)

    # -------------------------------------------------------- ray sync
    def _sync_ray(self, ray_view: Dict[str, List[Dict]], cloud) -> None:
        for inst in self.im.instances(ALLOCATED):
            alive = [
                n for n in ray_view.get(inst.instance_id, []) if n["alive"]
            ]
            # A slice runs only when EVERY host has joined (atomic).
            if len(alive) >= inst.hosts:
                inst.node_id = alive[0]["node_id"]
                self.im.transition(inst, RAY_RUNNING)
        for inst in self.im.instances(RAY_RUNNING):
            alive = [
                n for n in ray_view.get(inst.instance_id, []) if n["alive"]
            ]
            # Losing ANY host kills the whole slice.
            if len(alive) < inst.hosts:
                self.im.transition(inst, RAY_STOPPED)
        for inst in self.im.instances(RAY_STOPPED):
            self.im.transition(inst, TERMINATING)
            if inst.cloud_instance_id:
                self.provider.terminate(inst.cloud_instance_id)
            self.im.transition(inst, TERMINATED)
            self._draining.discard(inst.instance_id)

    # -------------------------------------------------------- scale up
    def _pending_shapes(self, reply) -> List[Dict[str, float]]:
        shapes = list(reply["task_demands"])
        for bundle_list in reply["pg_demands"]:
            shapes.extend(bundle_list)
        # Head/gang resources exist on exactly one host per slice; fit
        # those shapes first so a plain bundle never squats the head
        # host and forces a spurious extra slice.
        return sorted(
            (s for s in shapes if s),
            key=lambda s: not any(k.endswith("-head") for k in s),
        )

    def _scale_up(self, reply, nodes: List[Dict[str, Any]]) -> None:
        demands = self._pending_shapes(reply)
        if not demands:
            return
        # The demand list is the scheduler's whole pending queue — a
        # shape that fits an alive node's FREE capacity will be placed
        # as soon as a worker spawns, and capacity already launched but
        # not yet serving counts too (otherwise every tick re-launches
        # the same need while a daemon is still registering).
        capacities: List[Dict[str, float]] = [
            dict(n["available"]) for n in nodes if n["alive"]
        ]
        for i in self.im.instances(QUEUED, REQUESTED, ALLOCATED):
            cfg = self.node_types.get(i.node_type, {"resources": i.resources})
            capacities.extend(self._host_capacities(cfg))
        to_launch: List[str] = []
        counts: Dict[str, int] = {}
        for i in self.im.instances():
            if i.status not in (TERMINATED, ALLOCATION_FAILED):
                counts[i.node_type] = counts.get(i.node_type, 0) + 1
        for shape in demands:
            placed = False
            for cap in capacities:
                if _fits(cap, shape):
                    for k, v in shape.items():
                        cap[k] -= v
                    placed = True
                    break
            if placed:
                continue
            for t, cfg in self.node_types.items():
                if counts.get(t, 0) + to_launch.count(t) >= cfg.get(
                    "max_workers", 10
                ):
                    continue
                host_caps = self._host_capacities(cfg)
                hit = next(
                    (c for c in host_caps if _fits(c, shape)), None
                )
                if hit is not None:
                    for k, v in shape.items():
                        hit[k] -= v
                    # Remaining bundles of the same gang can land on
                    # the other hosts of this pending slice.
                    capacities.extend(host_caps)
                    to_launch.append(t)
                    break
        for t in to_launch:
            cfg = self.node_types[t]
            inst = self.im.create(
                t, cfg["resources"], hosts=cfg.get("hosts", 1)
            )
            self._launch(inst)
        # Re-launch retried instances.
        for inst in self.im.instances(QUEUED):
            self._launch(inst)

    @staticmethod
    def _host_capacities(cfg: Dict[str, Any]) -> List[Dict[str, float]]:
        """Per-host capacity dicts for a node type (slice types have
        several hosts; host 0 carries the gang head resource)."""
        hosts = cfg.get("hosts", 1)
        caps = [dict(cfg["resources"]) for _ in range(hosts)]
        head = cfg.get("head_resource")
        if head:
            caps[0][head] = caps[0].get(head, 0) + 1.0
        return caps

    def _launch(self, inst: Instance) -> None:
        inst.launch_attempts += 1
        try:
            cloud_id = self.provider.launch(inst)
        except Exception:  # noqa: BLE001 - provider failure -> retry
            self.im.transition(inst, REQUESTED)
            self.im.transition(inst, ALLOCATION_FAILED)
            return
        inst.cloud_instance_id = cloud_id
        self.im.transition(inst, REQUESTED)

    # ------------------------------------------------------ scale down
    def _scale_down(self, reply, ray_view: Dict[str, List[Dict]],
                    now: float) -> None:
        idle_node_ids = set(reply.get("idle_nodes", []))
        for inst in self.im.instances(RAY_RUNNING):
            if inst.instance_id in self._draining:
                continue
            nodes = ray_view.get(inst.instance_id)
            if not nodes:
                continue
            if all(n["node_id"] in idle_node_ids for n in nodes):
                since = self._idle_since.setdefault(inst.instance_id, now)
                if now - since >= self.idle_timeout_s:
                    from .._private.worker import drain_node

                    for n in nodes:
                        drain_node(
                            n["node_id"],
                            reason="autoscaler v2 idle scale-down",
                            deadline_s=self.drain_deadline_s,
                        )
                    self._draining.add(inst.instance_id)
                    self._idle_since.pop(inst.instance_id, None)
            else:
                self._idle_since.pop(inst.instance_id, None)

    # ----------------------------------------------------------- status
    def summary(self) -> Dict[str, Any]:
        by_status: Dict[str, int] = {}
        for i in self.im.instances():
            by_status[i.status] = by_status.get(i.status, 0) + 1
        return {
            "instances": by_status,
            "draining": len(self._draining),
        }
