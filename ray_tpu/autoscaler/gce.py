"""GCE-shaped cloud provider: the real API surface, offline-testable.

Reference: python/ray/autoscaler/_private/gcp/node_provider.py +
.../gcp/node.py (GCPCompute / GCPTPU split, operation polling,
label-filtered listing) — rebuilt against an injectable transport so
the v2 reconciler is exercised on *recorded response shapes* (this
environment has zero egress; the fixture transport replays the JSON
bodies the live API returns, including its error taxonomy).

The surface mirrors GCE semantics faithfully:

- mutations are ASYNC: ``instances.insert`` / ``tpu.nodes.create``
  return an operation that must be polled to DONE, and a DONE operation
  can still carry ``error`` (quota, stockout);
- errors are TYPED: HTTP 403 quotaExceeded, 409 alreadyExists,
  404 notFound, 429 rateLimit, 5xx backend — each with a distinct
  handling rule (retry / adopt / ignore / backoff);
- TPU slices are ATOMIC: one ``tpu.nodes`` resource with one
  networkEndpoint per host; a stocked-out or half-created node is
  rolled back whole (delete + raise) so quota never leaks.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from .v2 import CloudProvider, Instance

# HTTP status -> canonical GCE error reasons (the subset the provider
# must react to; reference gcp/node_provider.py error handling).
QUOTA_EXCEEDED = "quotaExceeded"
ALREADY_EXISTS = "alreadyExists"
NOT_FOUND = "notFound"
RATE_LIMITED = "rateLimitExceeded"
BACKEND_ERROR = "backendError"
STOCKOUT = "ZONE_RESOURCE_POOL_EXHAUSTED"


class GceApiError(Exception):
    """An HTTP-level or operation-level API failure."""

    def __init__(self, code: int, reason: str, message: str = ""):
        super().__init__(f"HTTP {code} {reason}: {message or reason}")
        self.code = code
        self.reason = reason

    @property
    def retryable(self) -> bool:
        """Transient for the reconciler's launch-retry/backoff loop.
        Quota and stockout ARE retryable — capacity frees up — while
        4xx request errors (bad template, permissions) are not."""
        return (
            self.code in (429, 500, 502, 503)
            or self.reason in (QUOTA_EXCEEDED, RATE_LIMITED, STOCKOUT)
        )


class GceCompute:
    """The mockable transport seam, method-per-endpoint (reference:
    gcp/node.py GCPCompute wraps googleapiclient's compute.instances()).
    Every method returns the decoded JSON body the REST API would."""

    def insert_instance(self, zone: str, body: Dict[str, Any]) -> Dict:
        raise NotImplementedError

    def delete_instance(self, zone: str, name: str) -> Dict:
        raise NotImplementedError

    def list_instances(self, zone: str, label_filter: Dict[str, str]) -> List[Dict]:
        raise NotImplementedError

    def get_operation(self, zone: str, op_name: str) -> Dict:
        raise NotImplementedError

    # --- TPU API (tpu.googleapis.com v2; nodes are slice-granular) ---
    def create_tpu_node(self, zone: str, node_id: str, body: Dict) -> Dict:
        raise NotImplementedError

    def delete_tpu_node(self, zone: str, node_id: str) -> Dict:
        raise NotImplementedError

    def list_tpu_nodes(self, zone: str, label_filter: Dict[str, str]) -> List[Dict]:
        raise NotImplementedError

    def get_tpu_operation(self, op_name: str) -> Dict:
        raise NotImplementedError


class GceNodeProvider(CloudProvider):
    """CloudProvider over the GCE surface.

    node_types config entries (per node type name):
      machine_type: "n2-standard-8"            (plain VM types)
      accelerator_type: "v5litepod-8"          (TPU slice types)
      hosts: N                                 (slice host count)
      source_image / disks / network: template passthrough
    """

    def __init__(
        self,
        api: GceCompute,
        *,
        cluster_name: str,
        zone: str,
        node_type_templates: Dict[str, Dict[str, Any]],
        op_poll_interval_s: float = 0.0,
        op_poll_limit: int = 120,
    ):
        self.api = api
        self.cluster_name = cluster_name
        self.zone = zone
        self.templates = node_type_templates
        self.op_poll_interval_s = op_poll_interval_s
        self.op_poll_limit = op_poll_limit

    # ------------------------------------------------------------ labels
    def _labels(self, inst: Instance) -> Dict[str, str]:
        # The label pair the reference uses to find its own nodes
        # (gcp/config.py: ray-cluster-name / ray-node-type).
        return {
            "ray-cluster-name": self.cluster_name,
            "ray-node-type": inst.node_type,
        }

    def _cluster_filter(self) -> Dict[str, str]:
        return {"ray-cluster-name": self.cluster_name}

    # --------------------------------------------------------- operations
    def _wait_operation(self, op: Dict, *, tpu: bool) -> Dict:
        """Poll an async mutation to DONE; a DONE op may itself carry a
        typed error (quota at insert time is synchronous 403, but
        stockouts surface HERE, on the completed operation)."""
        polls = 0
        while op.get("status") != "DONE":
            if polls >= self.op_poll_limit:
                raise GceApiError(
                    504, BACKEND_ERROR,
                    f"operation {op.get('name')} did not finish",
                )
            polls += 1
            if self.op_poll_interval_s:
                time.sleep(self.op_poll_interval_s)
            op = (
                self.api.get_tpu_operation(op["name"])
                if tpu
                else self.api.get_operation(self.zone, op["name"])
            )
        err = op.get("error")
        if err:
            first = (err.get("errors") or [{}])[0]
            raise GceApiError(
                int(op.get("httpErrorStatusCode", 409)),
                first.get("code", BACKEND_ERROR),
                first.get("message", ""),
            )
        return op

    # ------------------------------------------------------------- launch
    def launch(self, instance: Instance) -> str:
        tmpl = self.templates[instance.node_type]
        name = f"ray-{self.cluster_name}-{instance.instance_id}"
        if tmpl.get("accelerator_type"):
            return self._launch_tpu_slice(instance, name, tmpl)
        body = {
            "name": name,
            "machineType": tmpl.get("machine_type", "n2-standard-8"),
            "labels": self._labels(instance),
            "disks": tmpl.get("disks", []),
            "networkInterfaces": tmpl.get("network", []),
            "metadata": {
                "items": [
                    {"key": "ray-start", "value": tmpl.get("startup", "")}
                ]
            },
        }
        try:
            op = self.api.insert_instance(self.zone, body)
        except GceApiError as e:
            if e.reason == ALREADY_EXISTS:
                # Reconciler retried a launch whose first insert DID go
                # through (response lost): adopt the live instance
                # instead of erroring — names are deterministic.
                return name
            raise
        self._wait_operation(op, tpu=False)
        return name

    def _launch_tpu_slice(self, instance: Instance, name: str,
                          tmpl: Dict[str, Any]) -> str:
        body = {
            "acceleratorType": tmpl["accelerator_type"],
            "runtimeVersion": tmpl.get("runtime_version", "tpu-ubuntu2204-base"),
            "labels": self._labels(instance),
            "metadata": {"ray-start": tmpl.get("startup", "")},
        }
        try:
            op = self.api.create_tpu_node(self.zone, name, body)
        except GceApiError as e:
            if e.reason == ALREADY_EXISTS:
                return name
            raise
        try:
            self._wait_operation(op, tpu=True)
        except GceApiError:
            # Atomic slice: a stocked-out / failed create can leave a
            # half-provisioned node holding quota — roll it back whole
            # before surfacing the (retryable) error.
            try:
                self.api.delete_tpu_node(self.zone, name)
            except GceApiError as e2:
                if e2.reason != NOT_FOUND:
                    raise
            raise
        return name

    # ---------------------------------------------------------- terminate
    def terminate(self, cloud_instance_id: str) -> None:
        tpu = any(
            t.get("accelerator_type")
            for t in self.templates.values()
        ) and self._is_tpu_name(cloud_instance_id)
        try:
            if tpu:
                op = self.api.delete_tpu_node(self.zone, cloud_instance_id)
            else:
                op = self.api.delete_instance(self.zone, cloud_instance_id)
            self._wait_operation(op, tpu=tpu)
        except GceApiError as e:
            if e.reason == NOT_FOUND:
                return  # already gone: terminate is idempotent
            raise

    def _is_tpu_name(self, name: str) -> bool:
        # Reliable regardless of naming: ask the TPU listing.
        try:
            nodes = self.api.list_tpu_nodes(self.zone, self._cluster_filter())
        except GceApiError:
            return False
        return any(n.get("name", "").endswith(name) for n in nodes)

    # ------------------------------------------------------------ listing
    def running_instances(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for vm in self.api.list_instances(self.zone, self._cluster_filter()):
            if vm.get("status") == "RUNNING":
                out[vm["name"]] = {
                    "kind": "vm",
                    "node_type": vm.get("labels", {}).get("ray-node-type"),
                }
        for node in self.api.list_tpu_nodes(self.zone, self._cluster_filter()):
            if node.get("state") == "READY":
                short = node["name"].rsplit("/", 1)[-1]
                out[short] = {
                    "kind": "tpu",
                    "node_type": node.get("labels", {}).get("ray-node-type"),
                    "hosts": len(node.get("networkEndpoints", [])),
                }
        return out
