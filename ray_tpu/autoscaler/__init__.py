"""Autoscaler: scale logical nodes to unplaceable demand.

Reference: python/ray/autoscaler/v2 — an instance-manager loop reads
pending resource demand from the GCS (AutoscalerStateService,
autoscaler.proto:315), bin-packs it against node types, asks a
NodeProvider to launch/terminate instances, and downsizes idle nodes.
The FakeNodeProvider (reference:
autoscaler/_private/fake_multi_node/node_provider.py) "launches" nodes
as logical GCS nodes so the full loop is testable in one process; a
real TPU provider would create pod-slice VMs instead.
"""
from __future__ import annotations

from .autoscaler import Autoscaler, NodeProvider, FakeNodeProvider  # noqa: F401
from .v2 import (  # noqa: F401
    CloudProvider,
    InstanceManager,
    ProcessCloudProvider,
    Reconciler,
)

__all__ = [
    "Autoscaler",
    "CloudProvider",
    "FakeNodeProvider",
    "InstanceManager",
    "NodeProvider",
    "ProcessCloudProvider",
    "Reconciler",
]
