"""Autoscaler loop + node providers."""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional


class NodeProvider:
    """Plugin surface (reference: autoscaler/node_provider.py)."""

    def create_node(self, node_type: str, resources: Dict[str, float]) -> Any:
        raise NotImplementedError

    def terminate_node(self, node: Any) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[Any]:
        raise NotImplementedError


class FakeNodeProvider(NodeProvider):
    """Logical in-GCS nodes (reference fake_multi_node provider)."""

    def __init__(self):
        from ..cluster_utils import Cluster

        self._cluster = Cluster(initialize_head=False)
        self._nodes: List[Any] = []

    def create_node(self, node_type: str, resources: Dict[str, float]):
        node = self._cluster.add_node(
            num_cpus=resources.get("CPU", 1),
            num_tpus=resources.get("TPU", 0),
            resources={
                k: v for k, v in resources.items() if k not in ("CPU", "TPU")
            },
            label=f"autoscaled:{node_type}",
        )
        self._nodes.append(node)
        return node

    def terminate_node(self, node) -> None:
        self._cluster.remove_node(node)
        if node in self._nodes:
            self._nodes.remove(node)

    def non_terminated_nodes(self):
        return list(self._nodes)


from .._private.gcs import _fits  # same predicate the scheduler uses


class Autoscaler:
    """Reconcile unplaceable demand against node types.

    node_types: {name: {"resources": {...}, "max_workers": N}}.
    """

    def __init__(
        self,
        node_types: Dict[str, Dict[str, Any]],
        provider: Optional[NodeProvider] = None,
        *,
        idle_timeout_s: float = 30.0,
        interval_s: float = 1.0,
    ):
        self.node_types = node_types
        self.provider = provider or FakeNodeProvider()
        self.idle_timeout_s = idle_timeout_s
        self.interval_s = interval_s
        self._counts: Dict[str, int] = {t: 0 for t in node_types}
        self._node_type: Dict[bytes, str] = {}
        self._idle_since: Dict[bytes, float] = {}
        self._draining: set = set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.num_launches = 0
        self.num_terminations = 0

    # -------------------------------------------------------------- loop
    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.update()
            except Exception:  # noqa: BLE001 - survive transient errors
                pass
            self._stop.wait(self.interval_s)

    # ------------------------------------------------------------ update
    def _demand(self) -> Dict[str, Any]:
        from .._private.worker import global_client

        reply = global_client().request({"type": "get_pending_demand"})
        if not reply.get("ok"):
            raise RuntimeError("get_pending_demand failed")
        return reply

    def update(self):
        reply = self._demand()
        demands: List[Dict[str, float]] = list(reply["task_demands"])
        for bundle_list in reply["pg_demands"]:
            demands.extend(bundle_list)

        # Bin-pack unmet demand onto hypothetical new nodes (reference:
        # resource_demand_scheduler.py).
        to_launch: Dict[str, int] = {}
        capacities: List[Dict[str, float]] = []
        for shape in demands:
            if not shape:
                continue
            placed = False
            for cap in capacities:
                if _fits(cap, shape):
                    for k, v in shape.items():
                        cap[k] -= v
                    placed = True
                    break
            if placed:
                continue
            for t, cfg in self.node_types.items():
                if self._counts[t] + to_launch.get(t, 0) >= cfg.get(
                    "max_workers", 10
                ):
                    continue
                if _fits(cfg["resources"], shape):
                    cap = dict(cfg["resources"])
                    for k, v in shape.items():
                        cap[k] -= v
                    capacities.append(cap)
                    to_launch[t] = to_launch.get(t, 0) + 1
                    break
        for t, n in to_launch.items():
            for _ in range(n):
                node = self.provider.create_node(t, self.node_types[t]["resources"])
                self._counts[t] += 1
                self._node_type[node.node_id] = t
                self.num_launches += 1

        # Scale down nodes idle beyond the timeout: drain gracefully
        # first (no new placements; the GCS finalizes removal when the
        # node is quiet — reference: autoscaler DrainNode before
        # termination), then release the provider instance.
        from .._private.worker import global_client

        now = time.monotonic()
        idle = set(reply["idle_nodes"])
        alive = {
            n["node_id"]
            for n in global_client().cluster_info()["nodes"]
            if n["alive"]
        }
        for node in list(self.provider.non_terminated_nodes()):
            nid = node.node_id
            if nid in self._draining:
                if nid not in alive:  # drain finalized by the GCS
                    t = self._node_type.pop(nid, None)
                    if t:
                        self._counts[t] -= 1
                    self.provider.terminate_node(node)
                    self._draining.discard(nid)
                    self.num_terminations += 1
                continue
            if nid in idle:
                since = self._idle_since.setdefault(nid, now)
                if now - since >= self.idle_timeout_s:
                    from .._private.worker import drain_node

                    drain_node(
                        nid, reason="idle scale-down", deadline_s=30.0
                    )
                    self._draining.add(nid)
                    self._idle_since.pop(nid, None)
            else:
                self._idle_since.pop(nid, None)
