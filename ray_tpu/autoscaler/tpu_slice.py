"""TPU-slice cloud provider: atomic multi-host slices for autoscaler v2.

Reference: python/ray/autoscaler/_private/gcp/node_provider.py + the
GCE TPU queued-resource model. A TPU slice is ATOMIC: all its hosts are
created together and deleted together — there is no such thing as
"half a v5e-8". The provider therefore:

- launches a whole slice per instance (one Instance record == one
  slice of N hosts, each joining the cluster as its own node);
- rolls the entire slice back if ANY host fails to come up (partial
  creation must never leak quota — the reference's GCP provider
  deletes the queued resource on partial failure);
- terminates whole slices only.

The API surface (``TpuSliceApi``) is the mockable seam: the real
implementation would call the GCE TPU REST API; ``MockTpuSliceApi``
runs each "host VM" as a real node-daemon subprocess (the same
``ray_tpu._private.raylet`` a VM startup script would exec), with
injectable per-host creation failures — so the reconciler loop is
tested end-to-end against honest slice semantics on one box.
"""
from __future__ import annotations

import subprocess
import sys
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .v2 import CloudProvider, Instance


class PartialSliceError(RuntimeError):
    """Some hosts of a slice failed to create; the slice is unusable
    and must be rolled back whole."""

    def __init__(self, name: str, failed_hosts: List[int]):
        super().__init__(f"slice {name}: hosts {failed_hosts} failed")
        self.name = name
        self.failed_hosts = failed_hosts


@dataclass
class SliceType:
    """Shape of one sliceable node type (e.g. ``TPU-v5e-8``: 2 hosts x
    4 chips)."""

    accelerator: str  # e.g. "v5e-8"
    hosts: int
    host_resources: Dict[str, float]  # per host, e.g. {"CPU": 8, "TPU": 4}
    max_slices: int = 4

    @property
    def head_resource(self) -> str:
        # Worker 0 carries slice leadership (matches the accelerator
        # layer's synthetic gang resource, accelerators/tpu.py).
        return f"TPU-{self.accelerator}-head"

    def node_type_config(self) -> Dict[str, Any]:
        """The autoscaler v2 node_types entry for this slice type."""
        return {
            "resources": dict(self.host_resources),
            "hosts": self.hosts,
            "head_resource": self.head_resource,
            "max_workers": self.max_slices,
        }


class TpuSliceApi:
    """Mockable slice-granular cloud API (the GCE TPU surface shape:
    create/delete/list of whole slices, never individual hosts)."""

    def create_slice(self, name: str, accelerator: str,
                     host_commands: List[List[str]]) -> None:
        """Create all hosts of a slice; raises PartialSliceError if any
        host fails (leaving the survivors up, as a real partially-
        fulfilled queued resource would)."""
        raise NotImplementedError

    def delete_slice(self, name: str) -> None:
        """Tear down every host of the slice (idempotent)."""
        raise NotImplementedError

    def list_slices(self) -> Dict[str, Dict[str, Any]]:
        """name -> {"hosts": n_alive} for slices with any live host."""
        raise NotImplementedError


class MockTpuSliceApi(TpuSliceApi):
    """Each host "VM" is a real node-daemon subprocess. Failure
    injection: ``fail_next`` holds per-call lists of host indices that
    must fail to create (consumed one list per create_slice call)."""

    def __init__(self):
        self._slices: Dict[str, List[subprocess.Popen]] = {}
        self.fail_next: List[List[int]] = []
        self.create_calls = 0
        self.deleted: List[str] = []

    def create_slice(self, name, accelerator, host_commands):
        self.create_calls += 1
        failures = self.fail_next.pop(0) if self.fail_next else []
        procs: List[subprocess.Popen] = []
        failed: List[int] = []
        for i, cmd in enumerate(host_commands):
            if i in failures:
                failed.append(i)
                continue
            procs.append(
                subprocess.Popen(
                    cmd,
                    stdout=subprocess.DEVNULL,
                    stderr=subprocess.DEVNULL,
                )
            )
        self._slices[name] = procs
        if failed:
            raise PartialSliceError(name, failed)

    def delete_slice(self, name):
        for proc in self._slices.pop(name, []):
            if proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    proc.kill()
        self.deleted.append(name)

    def list_slices(self):
        return {
            name: {"hosts": sum(1 for p in procs if p.poll() is None)}
            for name, procs in self._slices.items()
            if any(p.poll() is None for p in procs)
        }

    def shutdown(self):
        for name in list(self._slices):
            self.delete_slice(name)


class TpuSliceProvider(CloudProvider):
    """Autoscaler v2 provider with whole-slice atomicity."""

    def __init__(
        self,
        api: TpuSliceApi,
        slice_types: Dict[str, SliceType],
        head_address: str,
        authkey: bytes,
        transfer_host: str = "127.0.0.1",
    ):
        self.api = api
        self.slice_types = slice_types
        self.head_address = head_address
        self.authkey = authkey
        self.transfer_host = transfer_host

    def node_types(self) -> Dict[str, Dict[str, Any]]:
        return {
            t: st.node_type_config() for t, st in self.slice_types.items()
        }

    def _host_command(self, instance: Instance, st: SliceType,
                      host_index: int) -> List[str]:
        import json

        resources = dict(st.host_resources)
        if host_index == 0:
            resources[st.head_resource] = 1.0
        return [
            sys.executable,
            "-m",
            "ray_tpu._private.raylet",
            "--address",
            self.head_address,
            "--authkey",
            self.authkey.hex(),
            "--resources",
            json.dumps(resources),
            "--label",
            f"v2:{instance.instance_id}:h{host_index}",
            "--transfer-host",
            self.transfer_host,
        ]

    def launch(self, instance: Instance) -> str:
        st = self.slice_types[instance.node_type]
        name = f"slice-{instance.instance_id}"
        cmds = [
            self._host_command(instance, st, i) for i in range(st.hosts)
        ]
        try:
            self.api.create_slice(name, st.accelerator, cmds)
        except PartialSliceError:
            # Atomic rollback: a partially-created slice is deleted
            # whole; the reconciler retries from QUEUED.
            self.api.delete_slice(name)
            raise
        return name

    def terminate(self, cloud_instance_id: str) -> None:
        self.api.delete_slice(cloud_instance_id)

    def running_instances(self) -> Dict[str, Any]:
        out = {}
        for name, meta in self.api.list_slices().items():
            out[name] = meta
        return out
