"""Workflow storage: task results + metadata on a filesystem.

Reference: python/ray/workflow/workflow_storage.py — results are
written atomically (tmp + rename) so a crash mid-write never yields a
corrupt "completed" marker.
"""
from __future__ import annotations

import json
import os
import pickle
import tempfile
import time
from typing import Any, Dict, List, Optional

DEFAULT_STORAGE = os.path.join(
    os.path.expanduser("~"), ".ray_tpu", "workflows"
)


class WorkflowStorage:
    def __init__(self, base: Optional[str] = None):
        self.base = base or os.environ.get(
            "RAY_TPU_WORKFLOW_STORAGE", DEFAULT_STORAGE
        )
        os.makedirs(self.base, exist_ok=True)

    # ------------------------------------------------------------- paths
    def _wf_dir(self, workflow_id: str) -> str:
        return os.path.join(self.base, workflow_id)

    def _task_result_path(self, workflow_id: str, task_id: str) -> str:
        return os.path.join(self._wf_dir(workflow_id), "tasks", f"{task_id}.pkl")

    def _status_path(self, workflow_id: str) -> str:
        return os.path.join(self._wf_dir(workflow_id), "status.json")

    # ----------------------------------------------------------- results
    def _atomic_write(self, path: str, data: bytes) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    def save_task_result(self, workflow_id: str, task_id: str, value: Any) -> None:
        self._atomic_write(
            self._task_result_path(workflow_id, task_id),
            pickle.dumps(value, protocol=5),
        )

    def has_task_result(self, workflow_id: str, task_id: str) -> bool:
        return os.path.exists(self._task_result_path(workflow_id, task_id))

    def load_task_result(self, workflow_id: str, task_id: str) -> Any:
        with open(self._task_result_path(workflow_id, task_id), "rb") as f:
            return pickle.load(f)

    # ------------------------------------------------------------ status
    def save_status(self, workflow_id: str, status: str,
                    extra: Optional[Dict[str, Any]] = None) -> None:
        payload = {"status": status, "updated_at": time.time(), **(extra or {})}
        self._atomic_write(
            self._status_path(workflow_id),
            json.dumps(payload).encode(),
        )

    def load_status(self, workflow_id: str) -> Optional[Dict[str, Any]]:
        try:
            with open(self._status_path(workflow_id)) as f:
                return json.load(f)
        except FileNotFoundError:
            return None

    def save_dag(self, workflow_id: str, dag_blob: bytes) -> None:
        self._atomic_write(
            os.path.join(self._wf_dir(workflow_id), "dag.pkl"), dag_blob
        )

    def load_dag(self, workflow_id: str) -> bytes:
        with open(os.path.join(self._wf_dir(workflow_id), "dag.pkl"), "rb") as f:
            return f.read()

    # -------------------------------------------------------------- list
    def list_workflows(self) -> List[str]:
        try:
            return sorted(
                d
                for d in os.listdir(self.base)
                if os.path.isdir(os.path.join(self.base, d))
            )
        except FileNotFoundError:
            return []

    def delete_workflow(self, workflow_id: str) -> None:
        import shutil

        shutil.rmtree(self._wf_dir(workflow_id), ignore_errors=True)
