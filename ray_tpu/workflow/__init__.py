"""ray_tpu.workflow: durable, resumable task DAGs.

Reference: python/ray/workflow/ (api.py:123 run, workflow_executor.py,
workflow_storage.py). A workflow executes a DAG of tasks with
every task's output persisted; re-running (``resume``) skips completed
tasks, giving exactly-once semantics across driver crashes.
"""
from __future__ import annotations

from .events import get_event, post_event, wait_for_event  # noqa: F401
from .api import (  # noqa: F401
    cancel,
    delete,
    get_output,
    get_status,
    init,
    list_all,
    resume,
    run,
    run_async,
)

__all__ = [
    "cancel",
    "get_event",
    "post_event",
    "wait_for_event",
    "delete",
    "get_output",
    "get_status",
    "init",
    "list_all",
    "resume",
    "run",
    "run_async",
]

from ray_tpu._private import usage_stats as _usage

_usage.record_library_usage("workflow")
