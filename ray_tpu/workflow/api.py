"""Workflow execution (reference: python/ray/workflow/api.py:123 run /
:177 run_async, workflow_executor.py, workflow_state_from_dag.py).

Each DAG node becomes a durable task: its result is persisted before
the workflow advances, keyed by a deterministic task id (topological
position + function name), so ``resume`` replays only what's missing.
"""
from __future__ import annotations

import threading
import uuid
from typing import Any, Dict, List, Optional

import cloudpickle

import ray_tpu
from ..dag import DAGNode, FunctionNode, InputNode
from .storage import WorkflowStorage

_storage: Optional[WorkflowStorage] = None
_lock = threading.Lock()


def init(storage_dir: Optional[str] = None) -> None:
    """Point workflow persistence at a directory (default
    ~/.ray_tpu/workflows or $RAY_TPU_WORKFLOW_STORAGE)."""
    global _storage
    with _lock:
        _storage = WorkflowStorage(storage_dir)


def _get_storage() -> WorkflowStorage:
    global _storage
    with _lock:
        if _storage is None:
            _storage = WorkflowStorage()
        return _storage


def _task_ids(dag: DAGNode) -> Dict[int, str]:
    """Deterministic per-node ids: topo position + name (reference:
    workflow_state_from_dag.py naming)."""
    ids = {}
    for i, node in enumerate(dag.topological_order()):
        if isinstance(node, InputNode):
            ids[id(node)] = f"{i}_input"
        elif isinstance(node, FunctionNode):
            ids[id(node)] = f"{i}_{node.fn_name}"
        else:
            ids[id(node)] = f"{i}_node"
    return ids


def _execute_durable(dag: DAGNode, workflow_id: str, storage: WorkflowStorage):
    ids = _task_ids(dag)
    if any(
        isinstance(n, InputNode) for n in dag.topological_order()
    ):
        raise ValueError(
            "workflow DAGs must be fully bound (no InputNode): "
            "workflow.run takes no runtime input"
        )
    cache: Dict[int, Any] = {}
    pending: List = []  # (task_id, node_key, ref) in topo order
    storage.save_status(workflow_id, "RUNNING")
    try:
        # Submit everything eagerly (refs as inputs → parallel branches
        # actually run in parallel); completed tasks short-circuit to
        # their stored values.
        for node in dag.topological_order():
            tid = ids[id(node)]
            if storage.has_task_result(workflow_id, tid):
                cache[id(node)] = storage.load_task_result(workflow_id, tid)
                continue
            ref_or_val = node._execute_node(cache, (), {})
            cache[id(node)] = ref_or_val
            pending.append((tid, id(node), ref_or_val))
        # Persist results as they materialize (topo order guarantees a
        # resume never sees a child persisted before its parents).
        for tid, key, ref in pending:
            value = (
                ray_tpu.get(ref) if isinstance(ref, ray_tpu.ObjectRef) else ref
            )
            storage.save_task_result(workflow_id, tid, value)
            cache[key] = value
        result = cache[id(dag)]
        storage.save_status(workflow_id, "SUCCESSFUL")
        return result
    except Exception as e:
        storage.save_status(workflow_id, "FAILED", {"error": repr(e)})
        raise


def run(dag: DAGNode, *, workflow_id: Optional[str] = None) -> Any:
    """Execute a DAG durably; blocks for the result."""
    storage = _get_storage()
    workflow_id = workflow_id or f"workflow-{uuid.uuid4().hex[:8]}"
    storage.save_dag(workflow_id, cloudpickle.dumps(dag))
    return _execute_durable(dag, workflow_id, storage)


def run_async(dag: DAGNode, *, workflow_id: Optional[str] = None):
    """Execute in a background thread; returns a concurrent Future."""
    import concurrent.futures

    storage = _get_storage()
    workflow_id = workflow_id or f"workflow-{uuid.uuid4().hex[:8]}"
    storage.save_dag(workflow_id, cloudpickle.dumps(dag))
    fut: concurrent.futures.Future = concurrent.futures.Future()

    def runner():
        try:
            fut.set_result(_execute_durable(dag, workflow_id, storage))
        except BaseException as e:  # noqa: BLE001
            fut.set_exception(e)

    threading.Thread(target=runner, daemon=True).start()
    return fut


def resume(workflow_id: str) -> Any:
    """Re-run a stored workflow; completed tasks are skipped
    (exactly-once across driver crashes)."""
    storage = _get_storage()
    dag = cloudpickle.loads(storage.load_dag(workflow_id))
    return _execute_durable(dag, workflow_id, storage)


def get_status(workflow_id: str) -> Optional[str]:
    meta = _get_storage().load_status(workflow_id)
    return meta["status"] if meta else None


def get_output(workflow_id: str) -> Any:
    """Last task's stored output of a SUCCESSFUL workflow."""
    storage = _get_storage()
    dag = cloudpickle.loads(storage.load_dag(workflow_id))
    ids = _task_ids(dag)
    return storage.load_task_result(workflow_id, ids[id(dag)])


def list_all() -> List[Dict[str, Any]]:
    storage = _get_storage()
    out = []
    for wid in storage.list_workflows():
        meta = storage.load_status(wid) or {}
        out.append({"workflow_id": wid, "status": meta.get("status", "UNKNOWN")})
    return out


def cancel(workflow_id: str) -> None:
    _get_storage().save_status(workflow_id, "CANCELED")


def delete(workflow_id: str) -> None:
    _get_storage().delete_workflow(workflow_id)
