"""Workflow event triggers: block a DAG node on an external event.

Reference: python/ray/workflow/http_event_provider.py +
event_listener.py — ``workflow.wait_for_event(...)`` inserts a node
that completes only when an external system posts the event, over HTTP
or from Python. Durability composes with the workflow executor: the
event payload lands in the GCS KV (surviving driver crashes), and once
the wait node completes its result persists like any task, so a resume
neither re-waits nor double-fires downstream work.

    recv = workflow.wait_for_event("order/123")
    final = process.bind(recv)
    workflow.run_async(final, workflow_id="order-123")
    # later, from anywhere (curl / another service):
    #   POST <dashboard>/api/workflow/events/order/123  {"paid": true}
"""
from __future__ import annotations

import json
import time
from typing import Any, Optional

import ray_tpu

EVENTS_NS = "workflow_events"


@ray_tpu.remote(num_cpus=0.01)
def _await_event_task(event_key: str, poll_interval_s: float,
                      timeout_s: Optional[float]):
    from ray_tpu._private.worker import global_client

    client = global_client()
    deadline = None if timeout_s is None else time.monotonic() + timeout_s
    while True:
        raw = client.kv_get(event_key.encode(), ns=EVENTS_NS)
        if raw is not None:
            # Consume: an event fires its waiter ONCE. Without this, a
            # recurring key (e.g. "deploy/done") would resolve every
            # future wait instantly with a stale payload. Durability is
            # unaffected: the wait node's result persists in workflow
            # storage the moment it completes.
            client.kv_del(event_key.encode(), ns=EVENTS_NS)
            return json.loads(raw)
        if deadline is not None and time.monotonic() > deadline:
            raise TimeoutError(
                f"workflow event {event_key!r} not posted within "
                f"{timeout_s}s"
            )
        time.sleep(poll_interval_s)


def wait_for_event(event_key: str, *, poll_interval_s: float = 0.2,
                   timeout_s: Optional[float] = None):
    """A DAG node resolving to the event's payload once posted.

    Delivery is one-shot: the waiter consumes the key, so reposting
    the same key fires the next waiter. Use one key per waiter (the
    reference couples listeners to workflow ids the same way)."""
    return _await_event_task.bind(event_key, poll_interval_s, timeout_s)


def post_event(event_key: str, payload: Any = None) -> None:
    """Deliver an event from Python (the HTTP provider does the same
    via the dashboard endpoint). Payload must be JSON-serializable."""
    from ray_tpu._private.worker import global_client

    global_client().kv_put(
        event_key.encode(), json.dumps(payload).encode(), ns=EVENTS_NS
    )


def get_event(event_key: str) -> Optional[Any]:
    from ray_tpu._private.worker import global_client

    raw = global_client().kv_get(event_key.encode(), ns=EVENTS_NS)
    return None if raw is None else json.loads(raw)
