"""ray-tpu CLI (reference: python/ray/scripts/scripts.py — ray
start/stop/status/list/timeline/memory/submit).

    python -m ray_tpu start --head --num-cpus 8   # standalone head
    python -m ray_tpu status
    python -m ray_tpu list actors
    python -m ray_tpu summary tasks
    python -m ray_tpu timeline -o trace.json
    python -m ray_tpu memory
    python -m ray_tpu submit -- python my_job.py
    python -m ray_tpu stop
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import tempfile
import time

SESSION_FILE = os.path.join(tempfile.gettempdir(), "ray_tpu",
                            "latest_session.json")


def _connect():
    import ray_tpu

    ray_tpu.init(address="auto")
    return ray_tpu


def cmd_start(args):
    if args.address:
        # Worker node: join an existing head over TCP as a node daemon
        # (reference: `ray start --address=<head>` starting a raylet).
        from ray_tpu._private import raylet

        daemon_args = ["--address", args.address]
        if args.authkey:
            daemon_args += ["--authkey", args.authkey]
        if args.num_cpus is not None:
            daemon_args += ["--num-cpus", str(args.num_cpus)]
        if args.num_tpus is not None:
            daemon_args += ["--num-tpus", str(args.num_tpus)]
        raylet.main(daemon_args)
        return

    import ray_tpu

    ray_tpu.init(
        num_cpus=args.num_cpus, num_tpus=args.num_tpus, tcp_port=args.port
    )
    from ray_tpu._private.worker import _global

    node = _global.node
    os.makedirs(os.path.dirname(SESSION_FILE), exist_ok=True)
    tmp = SESSION_FILE + f".tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(
            {
                "address": node.address,
                "tcp_address": node.tcp_address,
                "authkey": node.authkey.hex(),
                "pid": os.getpid(),
                "session_dir": node.session_dir,
            },
            f,
        )
    os.replace(tmp, SESSION_FILE)  # atomic: readers never see partial JSON
    print(f"ray_tpu head started: {node.address}")
    if node.tcp_address:
        print(f"network address: {node.tcp_address}")
        print(
            "join a node with: python -m ray_tpu start "
            f"--address={node.tcp_address} --authkey={node.authkey.hex()}"
        )
    print(f"session file: {SESSION_FILE}")
    print("connect with: ray_tpu.init(address='auto')")
    stop = [False]

    def on_term(*_):
        stop[0] = True

    signal.signal(signal.SIGTERM, on_term)
    signal.signal(signal.SIGINT, on_term)
    try:
        while not stop[0]:
            time.sleep(0.5)
    finally:
        try:
            os.unlink(SESSION_FILE)
        except FileNotFoundError:
            pass
        ray_tpu.shutdown()
        print("head stopped")


def cmd_stop(args):
    try:
        with open(SESSION_FILE) as f:
            info = json.load(f)
    except FileNotFoundError:
        print("no running head")
        return
    try:
        os.kill(info["pid"], signal.SIGTERM)
        print(f"sent SIGTERM to head pid {info['pid']}")
    except ProcessLookupError:
        print("head already gone")
        try:
            os.unlink(SESSION_FILE)
        except FileNotFoundError:
            pass


def cmd_status(args):
    ray_tpu = _connect()
    total = ray_tpu.cluster_resources()
    avail = ray_tpu.available_resources()
    from ray_tpu.util.state import list_nodes, list_workers

    nodes = list_nodes()
    workers = list_workers()
    print("== Cluster status ==")
    for k in sorted(total):
        print(f"  {avail.get(k, 0):g}/{total[k]:g} {k}")
    print(f"  nodes: {sum(1 for n in nodes if n['alive'])} alive"
          f" / {len(nodes)} total")
    print(f"  workers: {len(workers)}")


def cmd_drain(args):
    ray_tpu = _connect()
    node_id = bytes.fromhex(args.node_id)
    ok = ray_tpu.drain_node(
        node_id, reason=args.reason, deadline_s=args.deadline_s
    )
    print("drain accepted" if ok else "drain rejected (no such node)")
    return 0 if ok else 1


def _print_table(items, columns):
    if not items:
        print("(none)")
        return
    widths = {
        c: max(len(c), *(len(str(i.get(c, ""))) for i in items))
        for c in columns
    }
    print("  ".join(c.ljust(widths[c]) for c in columns))
    for i in items:
        print("  ".join(str(i.get(c, "")).ljust(widths[c]) for c in columns))


def cmd_usage(args):
    import json as _json

    from ray_tpu._private import usage_stats

    if not usage_stats.enabled():
        print("usage stats disabled (RAY_TPU_USAGE_STATS_ENABLED=0)")
        return 0
    rows = usage_stats.read_all()
    if not rows:
        print("no usage records (sink: local JSONL, zero egress)")
        return 0
    for r in rows[-20:]:
        print(_json.dumps(r))
    return 0


def cmd_debug(args):
    _connect()
    from ray_tpu.util import rpdb

    live = rpdb.sessions()
    if not live:
        print("no rpdb sessions waiting")
        return 1
    if args.session is None:
        if len(live) > 1:
            print("multiple sessions; pick one:")
            for name, addr in live:
                print(f"  {name}  {addr}")
            return 1
        name, addr = live[0]
    else:
        match = dict(live).get(args.session)
        if match is None:
            print(f"no session {args.session!r}; waiting: {live}")
            return 1
        name, addr = args.session, match
    print(f"attaching to {name} at {addr} (Ctrl-C to detach)")
    rpdb.bridge(addr)
    return 0


def cmd_list(args):
    _connect()
    from ray_tpu.util import state as state_api

    fn = getattr(state_api, f"list_{args.kind}")
    items = fn(limit=args.limit)
    columns = {
        "actors": ["actor_id", "name", "state", "class_name"],
        "tasks": ["task_id", "name", "state", "worker_id"],
        "nodes": ["node_id", "alive", "label", "total", "health_score",
                  "quarantined"],
        "workers": ["worker_id", "state", "pid", "num_inflight"],
        "objects": ["object_id", "status", "size", "inline"],
        "placement_groups": ["placement_group_id", "state", "strategy"],
    }[args.kind]
    _print_table(items, columns)


def cmd_nodes(args):
    """Per-node gray-failure health: scorer EWMA, quarantine flag, and
    the hedge won/lost scoreboard."""
    _connect()
    from ray_tpu.util.state import list_nodes

    items = list_nodes()
    for it in items:
        it["hedges_won_lost"] = (
            f"{it.get('hedges_won', 0)}/{it.get('hedges_lost', 0)}"
        )
    _print_table(
        items,
        ["node_id", "alive", "label", "health_score", "quarantined",
         "hedges_won_lost"],
    )


def cmd_summary(args):
    _connect()
    from ray_tpu.util.state import summarize_tasks

    print(json.dumps(summarize_tasks(), indent=2))


def cmd_timeline(args):
    _connect()
    from ray_tpu._private.state import timeline

    timeline(args.output)
    print(f"wrote {args.output} (open in chrome://tracing or perfetto)")


def cmd_events(args):
    """Flight-recorder transitions (submission → scheduling → lease →
    fork → exec → seal, plus worker/lease/object/transfer lifecycle)."""
    _connect()
    from ray_tpu.util.state import list_cluster_events

    if args.record is not None:
        from ray_tpu.util.state import set_events_recording

        set_events_recording(args.record == "on")
        print(f"flight recorder: recording {args.record}")
        return

    events = list_cluster_events(
        entity=args.task,
        category="task" if args.task else args.category,
        limit=args.limit,
    )
    if args.json:
        print(json.dumps(events, indent=2, default=str))
        return
    rows = [
        {
            "time": f"{e['timestamp']:.6f}",
            "category": e["category"],
            "event": e["event"],
            "entity": (e.get("entity") or "")[:16],
            "source": e.get("source", ""),
            "attrs": json.dumps(e.get("attrs") or {}, default=str),
        }
        for e in events
    ]
    _print_table(
        rows, ["time", "category", "event", "entity", "source", "attrs"]
    )


def cmd_memory(args):
    _connect()
    from ray_tpu.util.state import list_objects

    items = list_objects(limit=args.limit)
    total = sum(i["size"] for i in items)
    _print_table(items, ["object_id", "status", "size", "inline"])
    print(f"total: {len(items)} objects, {total / 1e6:.1f} MB")


def cmd_metrics(args):
    _connect()
    from ray_tpu.util.metrics import get_metrics_snapshot

    print(json.dumps(get_metrics_snapshot(), indent=2))


def cmd_dashboard(args):
    _connect()
    from ray_tpu.dashboard import start_dashboard

    url = start_dashboard(port=args.port)
    print(f"dashboard running at {url} (actor lives in the cluster)")


def cmd_submit(args):
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient()
    parts = args.entrypoint
    if parts and parts[0] == "--":  # argparse REMAINDER keeps the separator
        parts = parts[1:]
    entrypoint = " ".join(parts)
    job_id = client.submit_job(entrypoint=entrypoint)
    print(f"submitted {job_id}: {entrypoint}")
    if args.wait:
        status = client.wait_until_finish(job_id)
        print(client.get_job_logs(job_id), end="")
        print(f"job {job_id}: {status.value}")
        sys.exit(0 if status.value == "SUCCEEDED" else 1)


def cmd_jobs(args):
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient()
    _print_table(client.list_jobs(), ["job_id", "status", "entrypoint"])


def cmd_logs(args):
    """Recent worker stdout/stderr from the cluster's log ring
    (reference: `ray logs`)."""
    import os

    import ray_tpu
    from ray_tpu._private.worker import global_client

    # No live log subscription: the ring snapshot below would duplicate
    # every line that also arrived as a push.
    os.environ["RAY_TPU_LOG_TO_DRIVER"] = "0"
    ray_tpu.init(address=args.address or "auto", ignore_reinit_error=True)
    reply = global_client().request(
        {
            "type": "get_logs",
            "worker_prefix": args.worker or "",
            "tail": args.tail,
        }
    )
    for node, worker_tag, line in reply.get("lines", []):
        print(f"({node} worker={worker_tag}) {line}")


def cmd_serve_deploy(args):
    """Declarative deploy (reference: `serve deploy config.yaml`)."""
    import os

    import ray_tpu
    from ray_tpu import serve

    sys.path.insert(0, os.getcwd())
    os.environ["RAY_TPU_LOG_TO_DRIVER"] = "0"
    ray_tpu.init(address=args.address or "auto", ignore_reinit_error=True)
    handles = serve.deploy_config(args.config)
    print(f"deployed {len(handles)} application(s) from {args.config}")


def cmd_serve_status(args):
    import os

    import ray_tpu
    from ray_tpu import serve

    os.environ["RAY_TPU_LOG_TO_DRIVER"] = "0"
    ray_tpu.init(address=args.address or "auto", ignore_reinit_error=True)
    for name, info in serve.status().items():
        deps = ", ".join(
            f"{d}: {s.status.value} x{s.num_replicas}"
            for d, s in info.deployments.items()
        )
        print(f"{name}: {info.status.value}  [{deps}]")


def main(argv=None):
    p = argparse.ArgumentParser(prog="ray-tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser(
        "start", help="start a head (--head) or join one (--address)"
    )
    sp.add_argument("--head", action="store_true")
    sp.add_argument(
        "--address", default=None, help="head host:port to join as a node"
    )
    sp.add_argument("--authkey", default=None, help="cluster auth key (hex)")
    sp.add_argument(
        "--port",
        type=int,
        default=None,
        help="TCP port for the head's network control plane (0 = any)",
    )
    sp.add_argument("--num-cpus", type=int, default=None)
    sp.add_argument("--num-tpus", type=int, default=None)
    sp.set_defaults(fn=cmd_start)

    sub.add_parser("stop", help="stop the head").set_defaults(fn=cmd_stop)
    sub.add_parser("status", help="cluster status").set_defaults(fn=cmd_status)

    sp = sub.add_parser("drain", help="gracefully drain a node")
    sp.add_argument("node_id", help="node id (hex, from `list nodes`)")
    sp.add_argument("--reason", default="manual drain")
    sp.add_argument("--deadline-s", type=float, default=30.0)
    sp.set_defaults(fn=cmd_drain)

    sp = sub.add_parser("list", help="list cluster state")
    sp.add_argument("kind", choices=["actors", "tasks", "nodes", "workers",
                                     "objects", "placement_groups"])
    sp.add_argument("--limit", type=int, default=100)
    sp.set_defaults(fn=cmd_list)

    sp = sub.add_parser(
        "usage", help="show locally-recorded usage stats (never uploaded)"
    )
    sp.set_defaults(fn=cmd_usage)

    sp = sub.add_parser(
        "debug", help="attach to a waiting rpdb session (util/rpdb)"
    )
    sp.add_argument(
        "session", nargs="?", default=None,
        help="session name from the list (default: the only one)",
    )
    sp.set_defaults(fn=cmd_debug)

    sub.add_parser(
        "nodes", help="per-node health (gray-failure scorer)"
    ).set_defaults(fn=cmd_nodes)

    sp = sub.add_parser("summary", help="summarize tasks")
    sp.add_argument("kind", choices=["tasks"])
    sp.set_defaults(fn=cmd_summary)

    sp = sub.add_parser("timeline", help="dump chrome trace")
    sp.add_argument("-o", "--output", default="ray_tpu_timeline.json")
    sp.set_defaults(fn=cmd_timeline)

    sp = sub.add_parser(
        "events", help="flight-recorder runtime events"
    )
    sp.add_argument("--task", default=None, help="task id (hex) filter")
    sp.add_argument(
        "--category", default=None,
        help="category filter (task/worker/lease/object/transfer/sched)",
    )
    sp.add_argument("--limit", type=int, default=200)
    sp.add_argument("--json", action="store_true")
    sp.add_argument(
        "--record", choices=("on", "off"), default=None,
        help="toggle flight-recorder capture cluster-wide",
    )
    sp.set_defaults(fn=cmd_events)

    sp = sub.add_parser("memory", help="object store contents")
    sp.add_argument("--limit", type=int, default=100)
    sp.set_defaults(fn=cmd_memory)

    sub.add_parser("metrics", help="metrics snapshot").set_defaults(
        fn=cmd_metrics
    )

    sp = sub.add_parser("dashboard", help="start the dashboard")
    sp.add_argument("--port", type=int, default=8265)
    sp.set_defaults(fn=cmd_dashboard)

    sp = sub.add_parser("submit", help="submit a job")
    sp.add_argument("--wait", action="store_true")
    sp.add_argument("entrypoint", nargs=argparse.REMAINDER)
    sp.set_defaults(fn=cmd_submit)

    sub.add_parser("jobs", help="list jobs").set_defaults(fn=cmd_jobs)

    sp = sub.add_parser("logs", help="recent worker logs")
    sp.add_argument("--worker", default=None, help="worker id prefix filter")
    sp.add_argument("--tail", type=int, default=1000)
    sp.add_argument("--address", default=None, help="cluster address")
    sp.set_defaults(fn=cmd_logs)

    sp = sub.add_parser("serve", help="serve control (deploy/status)")
    serve_sub = sp.add_subparsers(dest="serve_cmd", required=True)
    spd = serve_sub.add_parser("deploy", help="deploy a YAML config")
    spd.add_argument("config", help="path to serve config YAML")
    spd.add_argument("--address", default=None, help="cluster address")
    spd.set_defaults(fn=cmd_serve_deploy)
    sps = serve_sub.add_parser("status", help="application statuses")
    sps.add_argument("--address", default=None, help="cluster address")
    sps.set_defaults(fn=cmd_serve_status)

    args = p.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
