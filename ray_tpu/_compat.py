"""Version-tolerant jax shims shared across ops/ and parallel/.

The repo targets current jax but must import (and dryrun on CPU) under
older releases where `shard_map` still lives in jax.experimental and
takes `check_rep` instead of `check_vma`.  Centralizing the probe here
keeps every call site on ONE spelling: ``shard_map(f, mesh=..., in_specs=...,
out_specs=..., check_vma=False)``.
"""
from __future__ import annotations

import functools
import inspect

try:
    from jax import shard_map as _shard_map
except ImportError:  # pre-0.6 jax: experimental namespace
    from jax.experimental.shard_map import shard_map as _shard_map

try:
    _SHARD_MAP_PARAMS = frozenset(inspect.signature(_shard_map).parameters)
except (TypeError, ValueError):  # C-level signature: trust the new API
    _SHARD_MAP_PARAMS = frozenset({"check_vma"})


try:
    from jax.lax import axis_size
except ImportError:  # pre-0.6 jax
    def axis_size(axis_name):
        """Static size of a manual mesh axis inside shard_map."""
        import jax.core as _core

        frame = _core.axis_frame(axis_name)
        # Newer 0.4.x returns the size directly; older returns a frame.
        return getattr(frame, "size", frame)


@functools.wraps(_shard_map)
def shard_map(f=None, /, **kwargs):
    if "check_vma" in kwargs and "check_vma" not in _SHARD_MAP_PARAMS:
        flag = kwargs.pop("check_vma")
        # Old spelling of the same replication/varying-manual-axes check.
        if "check_rep" in _SHARD_MAP_PARAMS:
            kwargs.setdefault("check_rep", flag)
    if "axis_names" in kwargs and "axis_names" not in _SHARD_MAP_PARAMS:
        # New API names the MANUAL axes; old API names the complement
        # (`auto`).  Translate via the mesh's full axis set.
        manual = frozenset(kwargs.pop("axis_names"))
        mesh = kwargs.get("mesh")
        if mesh is not None and "auto" in _SHARD_MAP_PARAMS:
            auto = frozenset(mesh.axis_names) - manual
            if auto:
                kwargs.setdefault("auto", auto)
    if f is None:  # used as a decorator factory: shard_map(mesh=...)(f)
        return lambda g: _shard_map(g, **kwargs)
    return _shard_map(f, **kwargs)
