"""Remote pdb for tasks and actors (reference: python/ray/util/rpdb.py).

``ray_tpu.util.rpdb.set_trace()`` inside remote code opens a debugger
server on the worker, registers it in the GCS KV under ``rpdb:<pid>``,
and blocks until a client attaches. ``ray_tpu debug`` (scripts/cli.py)
lists active sessions and bridges the terminal; programmatic clients
connect with :func:`connect` (what the test does).

The wire is a bare socket speaking pdb's own line protocol — no
custom framing, so `telnet`/`nc` also work.
"""
from __future__ import annotations

import os
import pdb
import socket
import sys
from typing import List, Optional, Tuple

_KV_PREFIX = b"rpdb:"


class _SockIO:
    """File-ish adapter pdb can read/write (readline-based)."""

    def __init__(self, sock: socket.socket):
        self._f = sock.makefile("rw", buffering=1)

    def readline(self):
        return self._f.readline()

    def write(self, data):
        self._f.write(data)
        return len(data)

    def flush(self):
        self._f.flush()


class _RemotePdb(pdb.Pdb):
    def __init__(self, io: _SockIO):
        super().__init__(stdin=io, stdout=io)
        self.use_rawinput = False
        self.prompt = "(rpdb) "


def _kv_put(key: bytes, value: bytes) -> None:
    from ray_tpu._private.worker import global_client

    global_client().request(
        {"type": "kv_put", "key": key, "value": value, "overwrite": True}
    )


def _kv_del(key: bytes) -> None:
    from ray_tpu._private.worker import global_client

    global_client().request({"type": "kv_del", "key": key})


def sessions() -> List[Tuple[str, str]]:
    """[(name, host:port)] of debugger sessions currently waiting."""
    from ray_tpu._private.worker import global_client

    reply = global_client().request(
        {"type": "kv_keys", "prefix": _KV_PREFIX}
    )
    out = []
    for key in reply.get("keys", []):
        val = global_client().request({"type": "kv_get", "key": key})
        v = val.get("value")
        if v:
            out.append((key[len(_KV_PREFIX):].decode(), v.decode()))
    return out


def set_trace(frame=None) -> None:
    """Break here and wait for one debugger client."""
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    # Bind all interfaces, advertise the node's reachable IP: a session
    # on another host must be attachable from the head's terminal.
    srv.bind(("0.0.0.0", 0))
    srv.listen(1)
    from ray_tpu._private import transport

    host = transport.node_ip()
    port = srv.getsockname()[1]
    name = f"{os.getpid()}"
    key = _KV_PREFIX + name.encode()
    _kv_put(key, f"{host}:{port}".encode())
    sys.stderr.write(
        f"rpdb: waiting for a debugger on {host}:{port} "
        f"(`ray_tpu debug` or `nc {host} {port}`)\n"
    )
    try:
        conn, _ = srv.accept()
    finally:
        _kv_del(key)
        srv.close()
    io = _SockIO(conn)
    dbg = _RemotePdb(io)
    dbg.set_trace(frame or sys._getframe().f_back)


def connect(addr: str) -> socket.socket:
    """Programmatic attach: returns the connected socket."""
    host, _, port = addr.rpartition(":")
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.connect((host, int(port)))
    return s


def bridge(addr: str) -> None:
    """Interactive attach: stdin -> socket, socket -> stdout (the CLI's
    `ray_tpu debug` loop)."""
    import threading

    s = connect(addr)

    def pump_in():
        try:
            for line in sys.stdin:
                s.sendall(line.encode())
        except (OSError, ValueError):
            pass

    t = threading.Thread(target=pump_in, daemon=True)
    t.start()
    try:
        while True:
            data = s.recv(4096)
            if not data:
                break
            sys.stdout.write(data.decode(errors="replace"))
            sys.stdout.flush()
    except KeyboardInterrupt:
        pass
    finally:
        s.close()
