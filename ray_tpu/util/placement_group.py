"""Placement groups: gang resource reservation.

Reference: python/ray/util/placement_group.py:145 and the GCS-side 2PC
scheduler (gcs_placement_group_scheduler.h:113). With the resource
authority centralized in this rebuild's GCS, reservation is a single
atomic transaction; the strategies (PACK/SPREAD/STRICT_*) keep reference
semantics. On TPU topologies, a PG with one bundle per host of a slice is
the gang-scheduling unit (reference's synthetic ``TPU-{pod}-head``
resource — accelerators/tpu.py:334 — maps to a ``TPU-<slice>-head``
custom resource here).
"""
from __future__ import annotations

from typing import Dict, List, Optional

from .._private.ids import PlacementGroupID
from .._private.worker import global_client

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]]):
        self.id = pg_id
        self._bundles = bundles

    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        return list(self._bundles)

    @property
    def bundle_count(self) -> int:
        return len(self._bundles)

    def ready(self) -> bool:
        info = global_client().request(
            {"type": "placement_group_info", "pg_id": self.id.binary()}
        )
        return bool(info.get("ok")) and info.get("state") == "CREATED"

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        """Block until every bundle is reserved. PGs can sit PENDING
        (capacity busy, or an autoscaler still adding nodes —
        reference: gcs_placement_group_manager pending queue). The
        request parks at the GCS and is answered on the state
        transition — no polling."""
        try:
            reply = global_client().request(
                {"type": "wait_placement_group", "pg_id": self.id.binary()},
                timeout=timeout_seconds,
            )
        except Exception:  # noqa: BLE001 - timeout
            return False
        return bool(reply.get("ok")) and reply.get("state") == "CREATED"

    def bundle_placements(self) -> List[Optional[bytes]]:
        info = global_client().request(
            {"type": "placement_group_info", "pg_id": self.id.binary()}
        )
        if not info.get("ok"):
            return []
        return [b["node_id"] for b in info["bundles"]]

    def __reduce__(self):
        return (PlacementGroup, (self.id, self._bundles))


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: str = "",
) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"Invalid strategy {strategy}; one of {VALID_STRATEGIES}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty dicts")
    pg_id = PlacementGroupID.from_random()
    reply = global_client().request(
        {
            "type": "create_placement_group",
            "pg_id": pg_id.binary(),
            "bundles": [{k: float(v) for k, v in b.items()} for b in bundles],
            "strategy": strategy,
            "name": name,
        }
    )
    if not reply.get("ok"):
        from ..exceptions import PlacementGroupSchedulingError

        raise PlacementGroupSchedulingError(reply.get("error", "unschedulable"))
    return PlacementGroup(pg_id, bundles)


def remove_placement_group(pg: PlacementGroup) -> None:
    global_client().request(
        {"type": "remove_placement_group", "pg_id": pg.id.binary()}
    )
