"""joblib backend over ray_tpu: ``register_ray()`` +
``joblib.parallel_backend("ray_tpu")`` fans sklearn/joblib workloads
over the cluster.

Reference: python/ray/util/joblib/__init__.py +
ray_backend.py (a Pool-backed joblib backend) — here implemented on
util.multiprocessing.Pool, whose sub-core actors co-host on shared
worker processes, so wide ``n_jobs`` stays cheap on small hosts.
"""
from __future__ import annotations

from typing import Any

__all__ = ["register_ray"]


def register_ray() -> None:
    """Register the "ray_tpu" joblib parallel backend."""
    from joblib.parallel import register_parallel_backend

    register_parallel_backend("ray_tpu", _RayTpuBackend)


def _make_backend():
    from joblib._parallel_backends import MultiprocessingBackend

    from ..multiprocessing import Pool

    class RayTpuBackend(MultiprocessingBackend):
        """joblib backend whose worker pool is cluster actors."""

        supports_timeout = True

        def effective_n_jobs(self, n_jobs):
            import ray_tpu

            if n_jobs == 1:
                return 1
            total = int(ray_tpu.cluster_resources().get("CPU", 1))
            if n_jobs is None or n_jobs == -1:
                return max(1, total)
            return n_jobs

        def configure(self, n_jobs=1, parallel=None, prefer=None,
                      require=None, **kwargs):
            n_jobs = self.effective_n_jobs(n_jobs)
            self.parallel = parallel
            self._pool = Pool(n_jobs)
            return n_jobs

        def _get_pool(self):
            return self._pool

        def terminate(self):
            pool = getattr(self, "_pool", None)
            if pool is not None:
                pool.terminate()
                self._pool = None

    return RayTpuBackend


class _RayTpuBackendMeta(type):
    """Defer the joblib import until the backend is instantiated."""

    def __call__(cls, *args: Any, **kwargs: Any):
        return _make_backend()(*args, **kwargs)


class _RayTpuBackend(metaclass=_RayTpuBackendMeta):
    pass
