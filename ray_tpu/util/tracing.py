"""Tracing: span context propagated through task submission.

Reference: util/tracing/tracing_helper.py:36-82 — OpenTelemetry spans
injected around _remote calls with context carried in the TaskSpec.
Zero-dependency equivalent: when RAY_TPU_TRACE=1, submissions stamp a
(trace_id, parent span) into the runtime_env env_vars and executions
record spans; spans export through the GCS KV and assemble into one
chrome-trace / parent-child tree with ``get_trace`` or
``ray_tpu timeline`` (task events already cover execution timing —
this adds cross-task causality).
"""
from __future__ import annotations

import json
import os
import time
import uuid
from typing import Any, Dict, List, Optional

_NS = "__traces__"
_TRACE_ENV = "RAY_TPU_TRACE_CTX"


def enabled() -> bool:
    return os.environ.get("RAY_TPU_TRACE", "0") == "1"


def current_context() -> Optional[Dict[str, str]]:
    blob = os.environ.get(_TRACE_ENV)
    return json.loads(blob) if blob else None


def new_context(name: str) -> Dict[str, str]:
    parent = current_context()
    return {
        "trace_id": parent["trace_id"] if parent else uuid.uuid4().hex[:16],
        "span_id": uuid.uuid4().hex[:8],
        "parent_span_id": parent["span_id"] if parent else "",
        "name": name,
    }


def inject(runtime_env: Optional[Dict[str, Any]], task_name: str):
    """Called at submission: thread the span context into the task's
    env so the worker's execution becomes a child span."""
    if not enabled():
        return runtime_env
    ctx = new_context(task_name)
    runtime_env = dict(runtime_env or {})
    env_vars = dict(runtime_env.get("env_vars") or {})
    env_vars[_TRACE_ENV] = json.dumps(ctx)
    env_vars["RAY_TPU_TRACE"] = "1"
    runtime_env["env_vars"] = env_vars
    return runtime_env


def record_span(name: str, start: float, end: float,
                ctx: Optional[Dict[str, str]] = None) -> None:
    if not enabled():
        return
    from .._private.worker import global_client, is_initialized

    if not is_initialized():
        return
    ctx = ctx or current_context() or new_context(name)
    span = {
        "name": name,
        "trace_id": ctx["trace_id"],
        "span_id": ctx["span_id"],
        "parent_span_id": ctx.get("parent_span_id", ""),
        "start": start,
        "end": end,
        "pid": os.getpid(),
    }
    global_client().kv_put(
        f"{ctx['trace_id']}:{ctx['span_id']}".encode(),
        json.dumps(span).encode(),
        ns=_NS,
    )


class span:
    """Context manager for user code: ``with tracing.span("step"): ...``"""

    def __init__(self, name: str):
        self.name = name
        self._ctx = None
        self._start = 0.0
        self._saved = None

    def __enter__(self):
        self._ctx = new_context(self.name)
        self._start = time.time()
        self._saved = os.environ.get(_TRACE_ENV)
        os.environ[_TRACE_ENV] = json.dumps(self._ctx)
        return self

    def __exit__(self, *exc):
        record_span(self.name, self._start, time.time(), self._ctx)
        if self._saved is None:
            os.environ.pop(_TRACE_ENV, None)
        else:
            os.environ[_TRACE_ENV] = self._saved
        return False


def get_trace(trace_id: Optional[str] = None) -> List[Dict[str, Any]]:
    """All spans (optionally one trace), sorted by start time."""
    from .._private.worker import global_client

    client = global_client()
    spans = []
    prefix = f"{trace_id}:".encode() if trace_id else b""
    for key in client.kv_keys(prefix, ns=_NS):
        blob = client.kv_get(key, ns=_NS)
        if blob:
            spans.append(json.loads(blob))
    return sorted(spans, key=lambda s: s["start"])
