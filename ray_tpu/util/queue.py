"""Actor-backed distributed queue (reference: python/ray/util/queue.py:20)."""
from __future__ import annotations

import time
from typing import Any, List, Optional


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int):
        import collections

        self._maxsize = maxsize
        self._items = collections.deque()

    def qsize(self) -> int:
        return len(self._items)

    def put_nowait(self, item) -> bool:
        if self._maxsize > 0 and len(self._items) >= self._maxsize:
            return False
        self._items.append(item)
        return True

    def get_nowait(self):
        if not self._items:
            return False, None
        return True, self._items.popleft()

    def put_nowait_batch(self, items: List[Any]) -> bool:
        if self._maxsize > 0 and len(self._items) + len(items) > self._maxsize:
            return False
        self._items.extend(items)
        return True

    def get_nowait_batch(self, num: int):
        taken = []
        while self._items and len(taken) < num:
            taken.append(self._items.popleft())
        return taken


class Queue:
    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        from .. import remote

        cls = remote(_QueueActor)
        if actor_options:
            cls = cls.options(**actor_options)
        self.actor = cls.remote(maxsize)

    def qsize(self) -> int:
        from .. import get

        return get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return self.qsize() == 0

    def put(self, item: Any, block: bool = True, timeout: Optional[float] = None):
        from .. import get

        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if get(self.actor.put_nowait.remote(item)):
                return
            if not block or (deadline and time.monotonic() > deadline):
                raise Full()
            time.sleep(0.01)

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        from .. import get as ray_get

        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ok, item = ray_get(self.actor.get_nowait.remote())
            if ok:
                return item
            if not block or (deadline and time.monotonic() > deadline):
                raise Empty()
            time.sleep(0.01)

    def put_nowait(self, item: Any):
        self.put(item, block=False)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def shutdown(self):
        from .. import kill

        kill(self.actor)
