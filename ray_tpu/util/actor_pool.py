"""ActorPool (reference: python/ray/util/actor_pool.py:13)."""
from __future__ import annotations

from typing import Any, Callable, Iterable, List


class ActorPool:
    def __init__(self, actors: List[Any]):
        self._idle = list(actors)
        self._future_to_actor = {}
        self._index_to_future = {}
        self._next_task_index = 0
        self._next_return_index = 0

    def submit(self, fn: Callable, value: Any):
        if not self._idle:
            raise RuntimeError("No idle actors; call get_next() first")
        actor = self._idle.pop()
        ref = fn(actor, value)
        self._future_to_actor[ref] = actor
        self._index_to_future[self._next_task_index] = ref
        self._next_task_index += 1

    def has_next(self) -> bool:
        return self._next_return_index < self._next_task_index

    def get_next(self, timeout: float | None = None) -> Any:
        from .. import get

        if not self.has_next():
            raise StopIteration("No pending results")
        ref = self._index_to_future[self._next_return_index]
        # Resolve before mutating bookkeeping so a GetTimeoutError leaves the
        # pool consistent and the result retrievable on retry.
        value = get(ref, timeout=timeout)
        del self._index_to_future[self._next_return_index]
        self._next_return_index += 1
        self._idle.append(self._future_to_actor.pop(ref))
        return value

    def has_free(self) -> bool:
        return bool(self._idle)

    def map(self, fn: Callable, values: Iterable[Any]):
        values = list(values)
        results = []
        it = iter(values)
        submitted = 0
        for v in it:
            if not self.has_free():
                break
            self.submit(fn, v)
            submitted += 1
        for v in list(values[submitted:]):
            results.append(self.get_next())
            self.submit(fn, v)
        while self.has_next():
            results.append(self.get_next())
        return results
