"""Out-of-graph collectives over the object store.

Reference: ray.util.collective (util/collective/collective.py —
GroupManager :40, init_collective_group :120, allreduce/allgather/
reducescatter/broadcast :258-615) with NCCL/GLOO backends and a named
rendezvous actor holding the ncclUniqueId (util/collective/util.py:9).

TPU mapping (SURVEY.md §5): *in-graph* collectives are XLA's job — psum
and friends compiled into pjit programs over ICI; this module is the
*out-of-graph* path for host-side tensor movement (weight broadcast to
CPU rollout actors, cross-slice DCN transfers). The rendezvous actor
became the group coordinator itself: an async actor that gathers each
round's contributions through the shared-memory object store, reduces
once, and hands every rank the result.

API intentionally mirrors the reference so user code ports 1:1.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu

_REDUCE_OPS = {
    "sum": lambda arrs: np.sum(arrs, axis=0),
    "mean": lambda arrs: np.mean(arrs, axis=0),
    "max": lambda arrs: np.max(arrs, axis=0),
    "min": lambda arrs: np.min(arrs, axis=0),
    "product": lambda arrs: np.prod(arrs, axis=0),
}


class _GroupCoordinator:
    """Async actor: one instance per collective group."""

    def __init__(self, world_size: int):
        import asyncio

        self.world_size = world_size
        self.rounds: Dict[str, Dict[int, Any]] = {}
        self.results: Dict[str, Any] = {}
        self.events: Dict[str, "asyncio.Event"] = {}

    def _event(self, key: str):
        import asyncio

        if key not in self.events:
            self.events[key] = asyncio.Event()
        return self.events[key]

    async def contribute(self, key: str, rank: int, payload: Any, op: str):
        contributions = self.rounds.setdefault(key, {})
        contributions[rank] = payload
        ev = self._event(key)
        if len(contributions) == self.world_size:
            ordered = [contributions[r] for r in range(self.world_size)]
            if op in _REDUCE_OPS:
                self.results[key] = _REDUCE_OPS[op](
                    [np.asarray(a) for a in ordered]
                )
            elif op == "gather":
                self.results[key] = [np.asarray(a) for a in ordered]
            elif op == "barrier":
                self.results[key] = True
            elif op == "broadcast":
                src = next(p for p in ordered if p is not None)
                self.results[key] = np.asarray(src)
            else:
                raise ValueError(f"unknown collective op {op}")
            ev.set()
        else:
            await ev.wait()
        return self.results[key]

    async def cleanup(self, key: str):
        self.rounds.pop(key, None)
        self.results.pop(key, None)
        self.events.pop(key, None)
        return True


class _GroupState:
    def __init__(self, name: str, world_size: int, rank: int, coordinator):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.coordinator = coordinator
        self.op_counter = 0
        self.lock = threading.Lock()

    def next_key(self, op: str) -> str:
        with self.lock:
            self.op_counter += 1
            return f"{op}:{self.op_counter}"


_groups: Dict[str, _GroupState] = {}
_groups_lock = threading.Lock()


def init_collective_group(
    world_size: int,
    rank: int,
    backend: str = "xla",
    group_name: str = "default",
) -> None:
    """Join a collective group; every member must call this
    (reference: init_collective_group :120; NCCL rendezvous replaced by
    a named coordinator actor)."""
    if backend not in ("xla", "host"):
        raise ValueError(f"unsupported backend {backend!r} (xla|host)")
    coordinator_cls = ray_tpu.remote(_GroupCoordinator)
    coordinator = coordinator_cls.options(
        name=f"__collective_{group_name}", get_if_exists=True
    ).remote(world_size)
    with _groups_lock:
        _groups[group_name] = _GroupState(group_name, world_size, rank, coordinator)


def _group(group_name: str) -> _GroupState:
    with _groups_lock:
        g = _groups.get(group_name)
    if g is None:
        raise RuntimeError(
            f"collective group '{group_name}' not initialized in this process"
        )
    return g


def _to_host(tensor: Any) -> np.ndarray:
    return np.asarray(tensor)


def allreduce(tensor: Any, group_name: str = "default", op: str = "sum") -> np.ndarray:
    g = _group(group_name)
    key = g.next_key(f"allreduce_{op}")
    return ray_tpu.get(
        g.coordinator.contribute.remote(key, g.rank, _to_host(tensor), op)
    )


def allgather(tensor: Any, group_name: str = "default") -> List[np.ndarray]:
    g = _group(group_name)
    key = g.next_key("allgather")
    return ray_tpu.get(
        g.coordinator.contribute.remote(key, g.rank, _to_host(tensor), "gather")
    )


def reducescatter(tensor: Any, group_name: str = "default", op: str = "sum") -> np.ndarray:
    g = _group(group_name)
    key = g.next_key(f"reducescatter_{op}")
    full = ray_tpu.get(
        g.coordinator.contribute.remote(key, g.rank, _to_host(tensor), op)
    )
    return np.array_split(full, g.world_size, axis=0)[g.rank]


def broadcast(tensor: Optional[Any], src_rank: int = 0, group_name: str = "default") -> np.ndarray:
    g = _group(group_name)
    key = g.next_key("broadcast")
    payload = _to_host(tensor) if g.rank == src_rank else None
    return ray_tpu.get(
        g.coordinator.contribute.remote(key, g.rank, payload, "broadcast")
    )


def barrier(group_name: str = "default") -> None:
    g = _group(group_name)
    key = g.next_key("barrier")
    ray_tpu.get(g.coordinator.contribute.remote(key, g.rank, None, "barrier"))


def destroy_collective_group(group_name: str = "default") -> None:
    with _groups_lock:
        g = _groups.pop(group_name, None)
    if g is not None and g.rank == 0:
        try:
            ray_tpu.kill(g.coordinator)
        except Exception:
            pass
