from .placement_group import (  # noqa: F401
    PlacementGroup,
    placement_group,
    remove_placement_group,
)
from .scheduling_strategies import PlacementGroupSchedulingStrategy  # noqa: F401
from .actor_pool import ActorPool  # noqa: F401
from .queue import Queue  # noqa: F401
