"""Channelized pubsub over the GCS (reference: src/ray/pubsub/ —
publisher.h per-channel subscriber registries, subscriber.h client
surface). Delivery is push on the process's persistent GCS connection;
callbacks run on the connection's reader thread, so keep them short
(hand off to your own queue/executor for real work).

Built-in channels published by the runtime:
  NODE_INFO  — node joins/deaths: {"state": "ALIVE"|"DEAD", ...}
  ACTOR      — actor lifecycle:   {"state": "ALIVE"|"DEAD", ...}

Arbitrary user channels work too:

    from ray_tpu.util import pubsub
    sub = pubsub.subscribe("my_channel", lambda key, data: print(key, data))
    pubsub.publish("my_channel", "k1", {"x": 1})
    sub.unsubscribe()
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

_lock = threading.Lock()
# channel -> list of (callback, key_prefix)
_subscribers: Dict[str, List[Tuple[Callable, str, "Subscription"]]] = {}
_installed = False


class Subscription:
    def __init__(self, channel: str, callback: Callable, key_prefix: str):
        self.channel = channel
        self._callback = callback
        self._key_prefix = key_prefix

    def unsubscribe(self) -> None:
        from .._private.worker import global_client

        with _lock:
            subs = _subscribers.get(self.channel, [])
            _subscribers[self.channel] = [
                s for s in subs if s[2] is not self
            ]
            empty = not _subscribers[self.channel]
        if empty:
            try:
                global_client().request(
                    {"type": "pubsub_unsubscribe", "channel": self.channel}
                )
            except Exception:  # noqa: BLE001 - cluster may be down
                pass


def _dispatch(msg: Dict[str, Any]) -> None:
    if msg.get("type") != "pubsub":
        return
    with _lock:
        subs = list(_subscribers.get(msg.get("channel", ""), ()))
    for callback, prefix, _ in subs:
        if prefix and not str(msg.get("key", "")).startswith(prefix):
            continue
        try:
            callback(msg.get("key"), msg.get("data"))
        except Exception:  # noqa: BLE001 - user callback must not kill reader
            pass


def _ensure_installed() -> None:
    """Chain our dispatcher onto the process's GCS push handler."""
    global _installed
    if _installed:
        return
    from .._private.worker import global_client

    client = global_client()
    prev = client._push_handler

    def chained(msg):
        _dispatch(msg)
        prev(msg)

    client._push_handler = chained
    _installed = True


def subscribe(
    channel: str,
    callback: Callable[[str, Any], None],
    *,
    key_prefix: str = "",
) -> Subscription:
    """Register a callback for a channel; returns a Subscription handle.
    The server-side registration happens once per (process, channel)."""
    from .._private.worker import global_client

    _ensure_installed()
    sub = Subscription(channel, callback, key_prefix)
    with _lock:
        subs = _subscribers.setdefault(channel, [])
        first = not subs
        subs.append((callback, key_prefix, sub))
    if first:
        global_client().request(
            {"type": "pubsub_subscribe", "channel": channel}
        )
    return sub


def publish(channel: str, key: str = "", data: Any = None) -> None:
    from .._private.worker import global_client

    global_client().request(
        {"type": "pubsub_publish", "channel": channel, "key": key,
         "data": data}
    )


def _reset_for_shutdown() -> None:
    """Called by ray_tpu.shutdown(): the client (and its chained push
    handler) is gone."""
    global _installed
    with _lock:
        _subscribers.clear()
    _installed = False
