"""User-defined metrics: Counter / Gauge / Histogram.

Reference: python/ray/util/metrics.py — metrics flow through the node's
metrics agent to Prometheus there; here each process publishes its
series into the GCS KV under a reserved namespace, and
``get_metrics_snapshot()`` (or the CLI ``ray-tpu metrics``) aggregates
across processes. Tag-based partitioning matches the reference API.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

_NS = "__metrics__"
_FLUSH_INTERVAL_S = 1.0


class _Registry:
    def __init__(self):
        self._metrics: Dict[str, "Metric"] = {}
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    def register(self, m: "Metric"):
        with self._lock:
            self._metrics[m.name] = m
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._flush_loop, daemon=True
                )
                self._thread.start()

    def _flush_loop(self):
        while True:
            time.sleep(_FLUSH_INTERVAL_S)
            try:
                self.flush()
            except Exception:  # noqa: BLE001 - cluster may be down
                pass

    def flush(self):
        from .._private.worker import global_client, is_initialized

        if not is_initialized():
            return
        with self._lock:
            payload = {
                name: m._dump() for name, m in self._metrics.items()
            }
        key = f"proc_{os.getpid()}".encode()
        global_client().kv_put(
            key, json.dumps(payload).encode(), ns=_NS
        )


_registry = _Registry()


class Metric:
    kind = "metric"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Tuple[str, ...] = ()):
        self.name = name
        self.description = description
        self._tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}
        self._series: Dict[Tuple, float] = {}
        self._lock = threading.Lock()
        _registry.register(self)

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: Optional[Dict[str, str]]) -> Tuple:
        merged = {**self._default_tags, **(tags or {})}
        return tuple(sorted(merged.items()))

    def _dump(self):
        with self._lock:
            return {
                "kind": self.kind,
                "description": self.description,
                "series": [
                    {"tags": dict(k), "value": v}
                    for k, v in self._series.items()
                ],
            }


class Counter(Metric):
    kind = "counter"

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        key = self._key(tags)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + value


class Gauge(Metric):
    kind = "gauge"

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        with self._lock:
            self._series[self._key(tags)] = float(value)


class Histogram(Metric):
    kind = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[List[float]] = None,
                 tag_keys: Tuple[str, ...] = ()):
        self.boundaries = sorted(boundaries or [0.1, 1, 10, 100, 1000])
        self._counts: Dict[Tuple, List[int]] = {}
        self._sums: Dict[Tuple, float] = {}
        super().__init__(name, description, tag_keys)

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        key = self._key(tags)
        with self._lock:
            counts = self._counts.setdefault(
                key, [0] * (len(self.boundaries) + 1)
            )
            idx = len(self.boundaries)
            for i, b in enumerate(self.boundaries):
                if value <= b:
                    idx = i
                    break
            counts[idx] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._series[key] = self._sums[key]  # sum as scalar series

    def _dump(self):
        with self._lock:
            return {
                "kind": self.kind,
                "description": self.description,
                "boundaries": self.boundaries,
                "series": [
                    {
                        "tags": dict(k),
                        "sum": self._sums.get(k, 0.0),
                        "counts": c,
                    }
                    for k, c in self._counts.items()
                ],
            }


def get_metrics_snapshot() -> Dict[str, Dict]:
    """Aggregate every process's published metrics from the GCS KV."""
    from .._private.worker import global_client

    client = global_client()
    _registry.flush()
    out: Dict[str, Dict] = {}
    for key in client.kv_keys(b"", ns=_NS):
        blob = client.kv_get(key, ns=_NS)
        if not blob:
            continue
        for name, dump in json.loads(blob).items():
            slot = out.setdefault(
                name, {"kind": dump["kind"],
                       "description": dump["description"], "series": []}
            )
            slot["series"].extend(dump.get("series", []))
    return out
