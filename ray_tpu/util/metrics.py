"""User-defined metrics: Counter / Gauge / Histogram.

Reference: python/ray/util/metrics.py — metrics flow through the node's
metrics agent to Prometheus there; here each process publishes its
series into the GCS KV under a reserved namespace, and
``get_metrics_snapshot()`` (or the CLI ``ray-tpu metrics``) aggregates
across processes. Tag-based partitioning matches the reference API.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

_NS = "__metrics__"
_FLUSH_INTERVAL_S = 1.0


class _Registry:
    def __init__(self):
        self._metrics: Dict[str, "Metric"] = {}
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    def register(self, m: "Metric"):
        with self._lock:
            self._metrics[m.name] = m
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._flush_loop, daemon=True
                )
                self._thread.start()

    def _flush_loop(self):
        while True:
            time.sleep(_FLUSH_INTERVAL_S)
            try:
                self.flush()
            except Exception:  # noqa: BLE001 - cluster may be down
                pass

    def flush(self):
        from .._private.worker import global_client, is_initialized

        if not is_initialized():
            return
        with self._lock:
            payload = {
                name: m._dump() for name, m in self._metrics.items()
            }
        key = f"proc_{os.getpid()}".encode()
        global_client().kv_put(
            key, json.dumps(payload).encode(), ns=_NS
        )


_registry = _Registry()


class Metric:
    kind = "metric"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Tuple[str, ...] = ()):
        self.name = name
        self.description = description
        self._tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}
        self._series: Dict[Tuple, float] = {}
        self._lock = threading.Lock()
        _registry.register(self)

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: Optional[Dict[str, str]]) -> Tuple:
        merged = {**self._default_tags, **(tags or {})}
        return tuple(sorted(merged.items()))

    def _dump(self):
        with self._lock:
            return {
                "kind": self.kind,
                "description": self.description,
                "series": [
                    {"tags": dict(k), "value": v}
                    for k, v in self._series.items()
                ],
            }


class Counter(Metric):
    kind = "counter"

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        key = self._key(tags)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + value


class Gauge(Metric):
    kind = "gauge"

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        with self._lock:
            self._series[self._key(tags)] = float(value)


class Histogram(Metric):
    kind = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[List[float]] = None,
                 tag_keys: Tuple[str, ...] = ()):
        self.boundaries = sorted(boundaries or [0.1, 1, 10, 100, 1000])
        self._counts: Dict[Tuple, List[int]] = {}
        self._sums: Dict[Tuple, float] = {}
        super().__init__(name, description, tag_keys)

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        key = self._key(tags)
        with self._lock:
            counts = self._counts.setdefault(
                key, [0] * (len(self.boundaries) + 1)
            )
            idx = len(self.boundaries)
            for i, b in enumerate(self.boundaries):
                if value <= b:
                    idx = i
                    break
            counts[idx] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._series[key] = self._sums[key]  # sum as scalar series

    def _dump(self):
        with self._lock:
            return {
                "kind": self.kind,
                "description": self.description,
                "boundaries": self.boundaries,
                "series": [
                    {
                        "tags": dict(k),
                        "sum": self._sums.get(k, 0.0),
                        "counts": c,
                    }
                    for k, c in self._counts.items()
                ],
            }


def _prom_name(name: str) -> str:
    out = []
    for ch in name:
        out.append(ch if ch.isalnum() or ch == "_" else "_")
    s = "".join(out)
    return ("_" + s) if s and s[0].isdigit() else s


def _escape_label_value(v) -> str:
    # Exposition format: label values escape backslash, double-quote
    # AND newline (a raw newline splits the sample line and corrupts
    # the whole scrape).
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _prom_labels(tags: Dict[str, str]) -> str:
    if not tags:
        return ""
    inner = ",".join(
        f'{_prom_name(k)}="{_escape_label_value(v)}"'
        for k, v in sorted(tags.items())
    )
    return "{" + inner + "}"


def prometheus_text(snapshot: Dict[str, Dict]) -> str:
    """Render a metrics snapshot in the Prometheus text exposition
    format (reference: the node metrics agent's OpenCensus→Prometheus
    exporter, _private/metrics_agent.py; format spec:
    prometheus.io/docs/instrumenting/exposition_formats)."""
    lines: List[str] = []
    for name, dump in sorted(snapshot.items()):
        pname = _prom_name(name)
        kind = dump.get("kind", "gauge")
        prom_type = {"counter": "counter", "histogram": "histogram"}.get(
            kind, "gauge"
        )
        if dump.get("description"):
            desc = dump["description"].replace("\n", " ")
            lines.append(f"# HELP {pname} {desc}")
        lines.append(f"# TYPE {pname} {prom_type}")
        if kind == "histogram":
            bounds = dump.get("boundaries", [])
            for s in dump.get("series", []):
                tags = s.get("tags", {})
                counts = s.get("counts", [])
                cum = 0
                for b, c in zip(bounds, counts):
                    cum += c
                    lines.append(
                        f"{pname}_bucket"
                        f"{_prom_labels({**tags, 'le': repr(float(b))})}"
                        f" {cum}"
                    )
                total = sum(counts)
                lines.append(
                    f"{pname}_bucket{_prom_labels({**tags, 'le': '+Inf'})}"
                    f" {total}"
                )
                lines.append(
                    f"{pname}_sum{_prom_labels(tags)} {s.get('sum', 0.0)}"
                )
                lines.append(f"{pname}_count{_prom_labels(tags)} {total}")
        else:
            for s in dump.get("series", []):
                lines.append(
                    f"{pname}{_prom_labels(s.get('tags', {}))}"
                    f" {s.get('value', 0.0)}"
                )
    return "\n".join(lines) + "\n"


def core_runtime_snapshot() -> Dict[str, Dict]:
    """Built-in runtime series computed live at scrape time (reference:
    stats/metric_defs.cc — tasks/actors/nodes/object-store gauges),
    merged into /metrics beside user-defined metrics."""
    from .._private.worker import global_client
    from . import state as state_api

    client = global_client()
    info = client.cluster_info()
    out: Dict[str, Dict] = {}

    def gauge(name, desc, series):
        out[name] = {"kind": "gauge", "description": desc, "series": series}

    gauge(
        "ray_tpu_resources_total",
        "cluster total resources by kind",
        [
            {"tags": {"resource": k}, "value": v}
            for k, v in info["total"].items()
        ],
    )
    gauge(
        "ray_tpu_resources_available",
        "cluster available resources by kind",
        [
            {"tags": {"resource": k}, "value": v}
            for k, v in info["available"].items()
        ],
    )
    gauge(
        "ray_tpu_nodes_alive",
        "alive cluster nodes",
        [{"tags": {}, "value": sum(1 for n in info["nodes"] if n["alive"])}],
    )
    workers = state_api.list_workers(limit=10_000)
    by_state: Dict[str, int] = {}
    for w in workers:
        by_state[w.get("state", "?")] = by_state.get(w.get("state", "?"), 0) + 1
    gauge(
        "ray_tpu_workers",
        "workers by state",
        [
            {"tags": {"state": s}, "value": c}
            for s, c in sorted(by_state.items())
        ],
    )
    tasks = state_api.summarize_tasks()
    by_state: Dict[str, int] = {}
    for states in tasks.get("by_func_name", {}).values():
        for s, c in states.items():
            by_state[s] = by_state.get(s, 0) + c
    # Gauge, not counter: per-state counts shrink as tasks transition
    # (RUNNING falls on every completion), and a shrinking counter
    # reads as a reset to Prometheus rate().
    out["ray_tpu_tasks"] = {
        "kind": "gauge",
        "description": "task events by state",
        "series": [
            {"tags": {"state": s}, "value": c}
            for s, c in sorted(by_state.items())
        ],
    }
    counts = client.request({"type": "msg_counts"}).get("counts", {})
    out["ray_tpu_control_messages"] = {
        "kind": "counter",
        "description": "head control-plane messages by type",
        "series": [
            {"tags": {"type": t}, "value": c}
            for t, c in sorted(counts.items())
        ],
    }
    out.update(flight_recorder_snapshot(client))
    return out


def flight_recorder_snapshot(client=None) -> Dict[str, Dict]:
    """Derived flight-recorder series (events.py aggregator): per-phase
    task latency histograms, event/drop counters, live pending-queue
    depth. Drops are the load-bearing series — ring overflow is counted
    at the source and summed here, never silently lost."""
    if client is None:
        from .._private.worker import global_client

        client = global_client()
    reply = client.request({"type": "events_summary"})
    if not reply.get("ok"):
        return {}
    s = reply["summary"]
    out: Dict[str, Dict] = {}
    out["ray_tpu_pending_tasks"] = {
        "kind": "gauge",
        "description": "tasks in the head scheduling queue",
        "series": [{"tags": {}, "value": s.get("queue_depth", 0)}],
    }
    out["ray_tpu_pending_scheduling_classes"] = {
        "kind": "gauge",
        "description": "distinct scheduling classes with queued tasks",
        "series": [{"tags": {}, "value": s.get("queue_classes", 0)}],
    }
    out["ray_tpu_flight_recorder_events_total"] = {
        "kind": "counter",
        "description": "flight-recorder transitions ingested by category",
        "series": [
            {"tags": {"category": c}, "value": n}
            for c, n in sorted(s.get("totals", {}).items())
        ],
    }
    # Always emit at least one sample so "no drops" is an observable 0,
    # not an absent series.
    drops = s.get("drops", {}) or {"": 0}
    out["ray_tpu_flight_recorder_dropped_total"] = {
        "kind": "counter",
        "description": "flight-recorder events dropped (ring overflow "
        "+ retention eviction) by source",
        "series": [
            {"tags": {"source": src} if src else {}, "value": n}
            for src, n in sorted(drops.items())
        ],
    }
    out["ray_tpu_task_phase_seconds"] = {
        "kind": "histogram",
        "description": "per-phase task latency "
        "(submit/queue/lease/fork/exec/seal)",
        "boundaries": list(s.get("phase_boundaries", [])),
        "series": [
            {
                "tags": {"phase": p},
                "sum": s.get("phase_sums", {}).get(p, 0.0),
                "counts": c,
            }
            for p, c in sorted(s.get("phase_counts", {}).items())
        ],
    }
    return out


def get_metrics_snapshot() -> Dict[str, Dict]:
    """Aggregate every process's published metrics from the GCS KV.

    Series from different processes that share a metric name AND tag
    set are MERGED (summed; histograms element-wise) — the Prometheus
    exposition format forbids duplicate samples for one labelset, and
    "total across processes" is the useful cluster-level reading
    (reference: the metrics agent aggregates per-worker streams the
    same way before export)."""
    from .._private.worker import global_client

    client = global_client()
    _registry.flush()
    out: Dict[str, Dict] = {}
    for key in client.kv_keys(b"", ns=_NS):
        blob = client.kv_get(key, ns=_NS)
        if not blob:
            continue
        for name, dump in json.loads(blob).items():
            slot = out.setdefault(
                name,
                {
                    "kind": dump["kind"],
                    "description": dump["description"],
                    "series": [],
                    "_by_tags": {},
                },
            )
            if "boundaries" in dump:
                slot["boundaries"] = dump["boundaries"]
            for s in dump.get("series", []):
                tkey = tuple(sorted((s.get("tags") or {}).items()))
                prev = slot["_by_tags"].get(tkey)
                if prev is None:
                    slot["_by_tags"][tkey] = merged = dict(s)
                    slot["series"].append(merged)
                elif "counts" in s:  # histogram: element-wise
                    prev["sum"] = prev.get("sum", 0.0) + s.get("sum", 0.0)
                    pc, sc = prev.get("counts", []), s.get("counts", [])
                    prev["counts"] = [
                        a + b
                        for a, b in zip(pc, sc)
                    ] if len(pc) == len(sc) else (pc or sc)
                else:
                    prev["value"] = prev.get("value", 0.0) + s.get(
                        "value", 0.0
                    )
    for slot in out.values():
        slot.pop("_by_tags", None)
    return out
