"""State API implementation."""
from __future__ import annotations

from collections import Counter
from typing import Any, Dict, List, Optional


def _list(kind: str, limit: int = 1000,
          filters: Optional[List[tuple]] = None) -> List[Dict[str, Any]]:
    from ..._private.worker import global_client

    for f in filters or []:
        if f[1] not in ("=", "!="):
            raise ValueError(f"unsupported filter op {f[1]!r}")
    # Filters apply server-side BEFORE the limit truncation so matches
    # beyond `limit` aren't silently dropped.
    reply = global_client().state_read(
        {"type": "list_state", "kind": kind, "limit": limit,
         "filters": [list(f) for f in filters or []]}
    )
    if not reply.get("ok"):
        raise RuntimeError(f"list_state({kind}) failed: {reply.get('error')}")
    return reply["items"]


def list_actors(filters=None, limit: int = 1000):
    return _list("actors", limit, filters)


def list_tasks(filters=None, limit: int = 1000):
    return _list("tasks", limit, filters)


def list_nodes(filters=None, limit: int = 1000):
    return _list("nodes", limit, filters)


def list_workers(filters=None, limit: int = 1000):
    return _list("workers", limit, filters)


def list_objects(filters=None, limit: int = 1000):
    return _list("objects", limit, filters)


def list_placement_groups(filters=None, limit: int = 1000):
    return _list("placement_groups", limit, filters)


def list_cluster_events(
    entity: Optional[str] = None,
    category: Optional[str] = None,
    job: Optional[str] = None,
    event: Optional[str] = None,
    limit: int = 1000,
) -> List[Dict[str, Any]]:
    """Flight-recorder transitions (reference: `ray list cluster-events`
    over the GCS task-event store; here events.py covers every layer
    boundary — submission, scheduling, lease, fork, exec, seal)."""
    from ..._private.state import list_cluster_events as _impl

    return _impl(
        entity=entity, category=category, job=job, event=event,
        limit=limit,
    )


def summarize_events() -> Dict[str, Any]:
    """Derived flight-recorder metrics: per-phase latency histograms,
    drop counters, live pending-queue depth."""
    from ..._private.worker import global_client

    reply = global_client().request({"type": "events_summary"})
    if not reply.get("ok"):
        raise RuntimeError("events_summary failed")
    return reply["summary"]


def set_events_recording(enabled: bool) -> None:
    """Toggle flight-recorder capture cluster-wide at runtime (head +
    every worker and node daemon), without a restart. Already-recorded
    events stay readable; only new captures stop."""
    from ..._private.worker import global_client

    reply = global_client().request(
        {"type": "set_events_recording", "enabled": bool(enabled)}
    )
    if not reply.get("ok"):
        raise RuntimeError("set_events_recording failed")


def summarize_tasks() -> Dict[str, Any]:
    """Per-function-name counts by state (reference:
    util/state/api.py summarize_tasks:1365)."""
    tasks = _list("tasks", limit=100_000)
    by_func: Dict[str, Counter] = {}
    for t in tasks:
        by_func.setdefault(t["name"], Counter())[t["state"]] += 1
    return {
        "total": len(tasks),
        "by_func_name": {
            name: dict(states) for name, states in sorted(by_func.items())
        },
    }
