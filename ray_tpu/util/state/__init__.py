"""State API: typed listing of cluster entities.

Reference: python/ray/util/state/api.py (list_actors:781,
list_tasks:1008, summarize_tasks:1365) — served there by the dashboard
StateHead + state aggregator over GCS; served here directly by the GCS.
``list_cluster_events`` / ``summarize_events`` read the flight
recorder (_private/events.py).
"""
from __future__ import annotations

from .api import (  # noqa: F401
    list_actors,
    list_cluster_events,
    list_nodes,
    list_objects,
    list_placement_groups,
    list_tasks,
    list_workers,
    set_events_recording,
    summarize_events,
    summarize_tasks,
)

__all__ = [
    "list_actors",
    "list_cluster_events",
    "list_nodes",
    "list_objects",
    "list_placement_groups",
    "list_tasks",
    "list_workers",
    "set_events_recording",
    "summarize_events",
    "summarize_tasks",
]
