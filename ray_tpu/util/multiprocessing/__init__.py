"""Drop-in ``multiprocessing.Pool`` over ray_tpu actors.

Reference: python/ray/util/multiprocessing/pool.py — the same idea
rebuilt small: a Pool of sub-core actors (they co-host on shared
worker processes — gcs._packable — so ``Pool(32)`` does not boot 32
interpreters), chunked dispatch, and the familiar map/imap/apply
surface. Library code written against multiprocessing parallelizes
across the cluster by changing one import.
"""
from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu

__all__ = ["Pool", "TimeoutError"]

TimeoutError = TimeoutError  # multiprocessing.TimeoutError parity


@ray_tpu.remote(num_cpus=0.2)
class _PoolWorker:
    def __init__(self, initializer=None, initargs=()):
        if initializer is not None:
            initializer(*initargs)

    def run_chunk(self, fn, chunk, star: bool):
        if star:
            return [fn(*args) for args in chunk]
        return [fn(x) for x in chunk]

    def run_one(self, fn, args, kwargs):
        return fn(*args, **kwargs)


class AsyncResult:
    def __init__(self, refs: List[Any], flatten: bool):
        self._refs = refs
        self._flatten = flatten

    def get(self, timeout: Optional[float] = None):
        chunks = ray_tpu.get(self._refs, timeout=timeout)
        if not self._flatten:
            return chunks[0]
        return list(itertools.chain.from_iterable(chunks))

    def wait(self, timeout: Optional[float] = None) -> None:
        ray_tpu.wait(
            list(self._refs), num_returns=len(self._refs), timeout=timeout
        )

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait(
            list(self._refs), num_returns=len(self._refs), timeout=0
        )
        return len(ready) == len(self._refs)

    def successful(self) -> bool:
        try:
            self.get(timeout=0.001)
            return True
        except Exception:  # noqa: BLE001 - multiprocessing semantics
            return False


class Pool:
    """multiprocessing.Pool surface over an actor fleet."""

    def __init__(self, processes: Optional[int] = None, initializer=None,
                 initargs=()):
        if processes is None:
            total = ray_tpu.cluster_resources().get("CPU", 1)
            processes = max(1, int(total))
        self._n = processes
        self._actors = [
            _PoolWorker.remote(initializer, tuple(initargs))
            for _ in range(processes)
        ]
        self._closed = False
        self._rr = 0
        # Every ref ever issued: join() waits on these so the standard
        # close()+join() shutdown both drains in-flight work AND tears
        # the actor fleet down (multiprocessing semantics — actors left
        # alive would leak their sub-core CPU reservations).
        self._issued: List[Any] = []

    # ------------------------------------------------------------ dispatch
    def _check(self):
        if self._closed:
            raise ValueError("Pool not running")

    def _chunks(self, values: List[Any], chunksize: Optional[int]):
        if chunksize is None:
            chunksize = max(1, len(values) // (self._n * 4) or 1)
        for i in range(0, len(values), chunksize):
            yield values[i : i + chunksize]

    def _spread(self, fn, chunks: Iterable[List[Any]], star: bool):
        refs = []
        for chunk in chunks:
            actor = self._actors[self._rr % self._n]
            self._rr += 1
            refs.append(actor.run_chunk.remote(fn, chunk, star))
        self._issued.extend(refs)
        return refs

    # ----------------------------------------------------------------- api
    def map(self, fn: Callable, values: Iterable[Any],
            chunksize: Optional[int] = None) -> List[Any]:
        return self.map_async(fn, values, chunksize).get()

    def map_async(self, fn, values, chunksize=None) -> AsyncResult:
        self._check()
        refs = self._spread(fn, self._chunks(list(values), chunksize), False)
        return AsyncResult(refs, flatten=True)

    def starmap(self, fn: Callable, values: Iterable[tuple],
                chunksize: Optional[int] = None) -> List[Any]:
        return self.starmap_async(fn, values, chunksize).get()

    def starmap_async(self, fn, values, chunksize=None) -> AsyncResult:
        self._check()
        refs = self._spread(fn, self._chunks(list(values), chunksize), True)
        return AsyncResult(refs, flatten=True)

    def apply(self, fn: Callable, args=(), kwds=None):
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn, args=(), kwds=None, callback=None,
                    error_callback=None) -> AsyncResult:
        """Callbacks fire from a waiter thread on completion — the
        contract joblib's PoolManagerMixin drives batches through."""
        self._check()
        actor = self._actors[self._rr % self._n]
        self._rr += 1
        ref = actor.run_one.remote(fn, tuple(args), kwds or {})
        self._issued.append(ref)
        result = AsyncResult([ref], flatten=False)
        if callback is not None or error_callback is not None:
            import threading

            def waiter():
                try:
                    value = result.get()
                except Exception as e:  # noqa: BLE001 - mp semantics
                    if error_callback is not None:
                        error_callback(e)
                    return
                if callback is not None:
                    callback(value)

            threading.Thread(target=waiter, daemon=True).start()
        return result

    def imap(self, fn: Callable, values: Iterable[Any],
             chunksize: Optional[int] = None):
        """Lazy ordered iterator: results stream as chunks finish."""
        self._check()
        refs = self._spread(fn, self._chunks(list(values), chunksize), False)
        for ref in refs:
            yield from ray_tpu.get(ref)

    def imap_unordered(self, fn: Callable, values: Iterable[Any],
                       chunksize: Optional[int] = None):
        self._check()
        refs = self._spread(fn, self._chunks(list(values), chunksize), False)
        pending = list(refs)
        while pending:
            ready, pending = ray_tpu.wait(pending, num_returns=1)
            yield from ray_tpu.get(ready[0])

    # ------------------------------------------------------------ lifecycle
    def close(self):
        self._closed = True

    def terminate(self):
        self._closed = True
        for a in self._actors:
            ray_tpu.kill(a)
        self._actors = []

    def join(self):
        if not self._closed:
            raise ValueError("Pool is still running")
        if self._issued:
            ray_tpu.wait(
                list(self._issued), num_returns=len(self._issued)
            )
            self._issued = []
        for a in self._actors:
            ray_tpu.kill(a)
        self._actors = []

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()
