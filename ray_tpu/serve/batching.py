"""@serve.batch: transparent request batching.

Reference: python/ray/serve/batching.py. Calls are queued; a background
task drains up to ``max_batch_size`` (or whatever arrived within
``batch_wait_timeout_s``) and invokes the wrapped function once with a
list of requests. On TPU this is the lever that keeps the MXU busy: a
replica's jitted model sees one padded batch instead of many size-1
calls.
"""
from __future__ import annotations

import asyncio
import functools
import inspect
from typing import Any, Callable, List, Optional


class _BatchQueue:
    def __init__(self, func, max_batch_size: int, batch_wait_timeout_s: float):
        self._func = func
        self._max = max_batch_size
        self._timeout = batch_wait_timeout_s
        self._queue: Optional[asyncio.Queue] = None
        self._task: Optional[asyncio.Task] = None

    def _ensure(self):
        if self._queue is None:
            self._queue = asyncio.Queue()
            self._task = asyncio.get_running_loop().create_task(self._loop())

    async def submit(self, item: Any) -> Any:
        self._ensure()
        fut = asyncio.get_running_loop().create_future()
        await self._queue.put((item, fut))
        return await fut

    async def _loop(self):
        while True:
            batch = [await self._queue.get()]
            deadline = asyncio.get_running_loop().time() + self._timeout
            while len(batch) < self._max:
                remaining = deadline - asyncio.get_running_loop().time()
                if remaining <= 0:
                    break
                try:
                    batch.append(
                        await asyncio.wait_for(self._queue.get(), timeout=remaining)
                    )
                except asyncio.TimeoutError:
                    break
            items = [b[0] for b in batch]
            futs = [b[1] for b in batch]
            try:
                results = self._func(items)
                if inspect.isawaitable(results):
                    results = await results
                if len(results) != len(items):
                    raise ValueError(
                        f"@serve.batch function returned {len(results)} results "
                        f"for {len(items)} inputs"
                    )
                for fut, res in zip(futs, results):
                    if not fut.done():
                        fut.set_result(res)
            except Exception as e:  # noqa: BLE001
                for fut in futs:
                    if not fut.done():
                        fut.set_exception(e)


def batch(
    _func: Optional[Callable] = None,
    *,
    max_batch_size: int = 10,
    batch_wait_timeout_s: float = 0.01,
):
    """Decorate an async method taking ``List[T] -> List[R]``; callers
    invoke it with a single ``T`` and get a single ``R``."""

    def wrap(func):
        queues = {}  # per-instance (methods) or single (functions)

        if _first_arg_is_self(func):

            @functools.wraps(func)
            async def method_wrapper(self, item):
                q = queues.get(id(self))
                if q is None:
                    q = _BatchQueue(
                        functools.partial(func, self), max_batch_size,
                        batch_wait_timeout_s,
                    )
                    queues[id(self)] = q
                return await q.submit(item)

            return method_wrapper

        q = _BatchQueue(func, max_batch_size, batch_wait_timeout_s)

        @functools.wraps(func)
        async def func_wrapper(item):
            return await q.submit(item)

        return func_wrapper

    if _func is not None:
        return wrap(_func)
    return wrap


def _first_arg_is_self(func) -> bool:
    params = list(inspect.signature(func).parameters)
    return bool(params) and params[0] == "self"
