"""Serve public API (reference: python/ray/serve/api.py).

``@serve.deployment`` wraps a class/function into a Deployment;
``.bind(*args)`` builds an Application graph (nested Applications in
the init args become DeploymentHandles — model composition);
``serve.run`` deploys it through the controller and blocks until
RUNNING.
"""
from __future__ import annotations

import pickle
import time
from dataclasses import replace as _dc_replace
from typing import Any, Callable, Dict, List, Optional, Union

import cloudpickle

from ._private.common import (
    CONTROLLER_NAME,
    DEFAULT_APP_NAME,
    ApplicationStatus,
    DeploymentID,
    PROXY_NAME_PREFIX,
)
from ._private.replica import get_replica_context  # noqa: F401 (re-export)
from .config import AutoscalingConfig, DeploymentConfig, GRPCOptions, HTTPOptions
from .handle import DeploymentHandle


class Application:
    """A deployment bound to init args (reference: serve/api.py
    Application) — the node of the composition graph."""

    def __init__(self, deployment: "Deployment", args: tuple, kwargs: dict):
        self._deployment = deployment
        self._args = args
        self._kwargs = kwargs


class Deployment:
    def __init__(self, func_or_class, name: str, config: DeploymentConfig):
        self._func_or_class = func_or_class
        self.name = name
        self._config = config

    def options(self, **kwargs) -> "Deployment":
        name = kwargs.pop("name", self.name)
        cfg_fields = {
            "num_replicas",
            "max_ongoing_requests",
            "max_queued_requests",
            "user_config",
            "autoscaling_config",
            "health_check_period_s",
            "health_check_timeout_s",
            "graceful_shutdown_timeout_s",
            "ray_actor_options",
        }
        updates = {}
        for k in list(kwargs):
            if k in cfg_fields:
                updates[k] = kwargs.pop(k)
        if kwargs:
            raise TypeError(f"Unknown deployment options: {sorted(kwargs)}")
        if isinstance(updates.get("autoscaling_config"), dict):
            updates["autoscaling_config"] = AutoscalingConfig(
                **updates["autoscaling_config"]
            )
        if updates.get("num_replicas") == "auto":
            updates["num_replicas"] = 1
            updates.setdefault("autoscaling_config", AutoscalingConfig(max_replicas=10))
        return Deployment(
            self._func_or_class, name, _dc_replace(self._config, **updates)
        )

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise RuntimeError(
            "Deployments cannot be called directly; use .bind() + serve.run, "
            "or a DeploymentHandle."
        )


def deployment(
    _func_or_class=None,
    *,
    name: Optional[str] = None,
    num_replicas: Union[int, str, None] = None,
    max_ongoing_requests: int = 100,
    max_queued_requests: int = -1,
    user_config: Any = None,
    autoscaling_config: Union[AutoscalingConfig, dict, None] = None,
    health_check_period_s: float = 2.0,
    health_check_timeout_s: float = 30.0,
    graceful_shutdown_timeout_s: float = 5.0,
    ray_actor_options: Optional[Dict[str, Any]] = None,
):
    """Decorator: ``@serve.deployment`` (reference serve/api.py:248)."""

    def build(target) -> Deployment:
        if isinstance(autoscaling_config, dict):
            asc = AutoscalingConfig(**autoscaling_config)
        else:
            asc = autoscaling_config
        n = num_replicas
        if n == "auto":
            n = 1
            nonlocal_asc = asc or AutoscalingConfig(max_replicas=10)
        else:
            nonlocal_asc = asc
        cfg = DeploymentConfig(
            num_replicas=n or 1,
            max_ongoing_requests=max_ongoing_requests,
            max_queued_requests=max_queued_requests,
            user_config=user_config,
            autoscaling_config=nonlocal_asc,
            health_check_period_s=health_check_period_s,
            health_check_timeout_s=health_check_timeout_s,
            graceful_shutdown_timeout_s=graceful_shutdown_timeout_s,
            ray_actor_options=ray_actor_options or {},
        )
        return Deployment(target, name or target.__name__, cfg)

    if _func_or_class is not None:
        return build(_func_or_class)
    return build


def ingress(app_or_func):
    """Compatibility shim: the reference wires FastAPI apps here; the
    aiohttp-native proxy calls ``__call__(HTTPRequest)`` directly, so
    this is the identity decorator."""
    return lambda cls: cls


# --------------------------------------------------------------- control
def _get_controller():
    from .. import get_actor

    return get_actor(CONTROLLER_NAME)


def start(http_options: Optional[HTTPOptions] = None, proxy: bool = True,
          grpc_options: Optional["GRPCOptions"] = None):
    """Ensure the controller (and HTTP/gRPC proxies) are running."""
    from .. import get, get_actor, is_initialized, init, remote

    if not is_initialized():
        init()
    try:
        return get_actor(CONTROLLER_NAME)
    except ValueError:
        pass
    from ._private.controller import ServeController

    http_options = http_options or HTTPOptions()
    controller = (
        remote(ServeController)
        .options(name=CONTROLLER_NAME, max_concurrency=64, get_if_exists=True)
        .remote(pickle.dumps(http_options))
    )
    controller.run_control_loop.remote()
    if proxy:
        from ._private.proxy import ProxyActor

        proxy_actor = (
            remote(ProxyActor)
            .options(
                name=f"{PROXY_NAME_PREFIX}::head",
                max_concurrency=256,
                get_if_exists=True,
            )
            .remote(http_options.host, http_options.port)
        )
        get(proxy_actor.ready.remote())
    if grpc_options is not None:
        from ._private.grpc_proxy import GrpcProxyActor

        grpc_actor = (
            remote(GrpcProxyActor)
            .options(
                name=f"{PROXY_NAME_PREFIX}::grpc",
                max_concurrency=256,
                get_if_exists=True,
            )
            .remote(grpc_options.host, grpc_options.port)
        )
        get(grpc_actor.ready.remote())
    return controller


def _flatten_application(
    app: Application, infos: Dict[str, dict], handles: Dict[int, DeploymentHandle],
    app_name: str,
) -> str:
    """DFS the composition graph; nested Applications become handles."""
    if id(app) in handles:
        return handles[id(app)].deployment_id.name
    dep = app._deployment

    def convert(v):
        if isinstance(v, Application):
            child = _flatten_application(v, infos, handles, app_name)
            return DeploymentHandle(child, app_name)
        return v

    args = tuple(convert(a) for a in app._args)
    kwargs = {k: convert(v) for k, v in app._kwargs.items()}
    if dep.name in infos:
        existing = infos[dep.name]
        if existing["_app_obj_id"] != id(app):
            raise ValueError(
                f"Duplicate deployment name {dep.name!r} in application"
            )
    infos[dep.name] = {
        "name": dep.name,
        "serialized_callable": cloudpickle.dumps(dep._func_or_class),
        "init_args": args,
        "init_kwargs": kwargs,
        "config": dep._config,
        "_app_obj_id": id(app),
    }
    handles[id(app)] = DeploymentHandle(dep.name, app_name)
    return dep.name


def run(
    target: Application,
    *,
    name: str = DEFAULT_APP_NAME,
    route_prefix: Optional[str] = "/",
    _blocking: bool = True,
    timeout_s: float = 120.0,
    deployment_overrides: Optional[Dict[str, Dict[str, Any]]] = None,
) -> DeploymentHandle:
    """Deploy an application; returns a handle to its ingress
    (reference serve/api.py:570). ``deployment_overrides`` maps
    deployment name -> config-field updates (the declarative-config
    path: YAML values override code-side settings, serve/schema.py)."""
    from .. import get

    if not isinstance(target, Application):
        raise TypeError("serve.run expects an Application (deployment.bind(...))")
    controller = start()
    infos: Dict[str, dict] = {}
    handles: Dict[int, DeploymentHandle] = {}
    ingress_name = _flatten_application(target, infos, handles, name)
    for dep_name, updates in (deployment_overrides or {}).items():
        if dep_name not in infos:
            raise ValueError(
                f"deployment override for unknown deployment {dep_name!r}; "
                f"application has {sorted(infos)}"
            )
        updates = dict(updates)
        if isinstance(updates.get("autoscaling_config"), dict):
            updates["autoscaling_config"] = AutoscalingConfig(
                **updates["autoscaling_config"]
            )
        infos[dep_name]["config"] = _dc_replace(
            infos[dep_name]["config"], **updates
        )
    payload = [
        {k: v for k, v in d.items() if k != "_app_obj_id"} for d in infos.values()
    ]
    get(
        controller.deploy_application.remote(
            name, route_prefix, ingress_name, pickle.dumps(payload)
        )
    )
    if _blocking:
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            statuses = get(controller.get_app_statuses.remote())
            info = statuses.get(name)
            if info and info.status == ApplicationStatus.RUNNING:
                break
            if info and info.status == ApplicationStatus.DEPLOY_FAILED:
                raise RuntimeError(f"Deploy failed: {info.message}")
            time.sleep(0.1)
        else:
            raise TimeoutError(f"Application {name!r} not RUNNING in {timeout_s}s")
    return DeploymentHandle(ingress_name, name)


def delete(name: str, _blocking: bool = True):
    from .. import get

    controller = _get_controller()
    get(controller.delete_application.remote(name))
    if _blocking:
        for _ in range(600):
            if name not in get(controller.get_app_statuses.remote()):
                return
            time.sleep(0.05)


def status():
    from .. import get

    return get(_get_controller().get_app_statuses.remote())


def get_app_handle(name: str = DEFAULT_APP_NAME) -> DeploymentHandle:
    from .. import get

    info = get(_get_controller().get_app_info.remote(name))
    if info is None:
        raise ValueError(f"No application named {name!r}")
    return DeploymentHandle(info["ingress"], name)


def get_deployment_handle(
    deployment_name: str, app_name: str = DEFAULT_APP_NAME
) -> DeploymentHandle:
    return DeploymentHandle(deployment_name, app_name)


def shutdown():
    """Tear down all Serve state (reference serve/api.py:120)."""
    from .. import get, get_actor, kill

    from ._private.router import shutdown_routers

    try:
        controller = get_actor(CONTROLLER_NAME)
    except ValueError:
        return
    try:
        get(controller.graceful_shutdown.remote(), timeout=30)
    except Exception:  # noqa: BLE001
        pass
    shutdown_routers()
    try:
        proxy = get_actor(f"{PROXY_NAME_PREFIX}::head")
        try:
            get(proxy.shutdown.remote(), timeout=5)
        except Exception:  # noqa: BLE001
            pass
        kill(proxy)
    except ValueError:
        pass
    kill(controller)


# ------------------------------------------------------------ multiplex
from .multiplex import get_multiplexed_model_id, multiplexed  # noqa: E402,F401
