"""Declarative Serve config: YAML -> running applications.

Reference: python/ray/serve/schema.py (ServeDeploySchema) + the `serve
deploy` CLI — a config file names applications by import path with
per-deployment option overrides, and redeploying an updated file
reconciles the live cluster toward it (replica counts change with zero
downtime: the deployment reconciler scales the existing replica set
instead of tearing the app down).

Schema::

    applications:
      - name: text_app            # default: "default"
        route_prefix: /           # null -> no HTTP route
        import_path: mymodule:app # module attr holding a bound Application
        runtime_env: {}           # reserved
        deployments:              # optional per-deployment overrides
          - name: TextGen
            num_replicas: 2
            max_ongoing_requests: 16
            autoscaling_config: {min_replicas: 1, max_replicas: 4}
"""
from __future__ import annotations

import importlib
from typing import Any, Dict, List

from .api import Application, run
from .handle import DeploymentHandle

_OVERRIDE_FIELDS = {
    "num_replicas",
    "max_ongoing_requests",
    "max_queued_requests",
    "user_config",
    "autoscaling_config",
    "health_check_period_s",
    "health_check_timeout_s",
    "graceful_shutdown_timeout_s",
    "ray_actor_options",
}


def _load_import_path(import_path: str) -> Application:
    module_name, _, attr = import_path.partition(":")
    if not attr:
        raise ValueError(
            f"import_path {import_path!r} must be 'module:variable'"
        )
    module = importlib.import_module(module_name)
    target = module
    for part in attr.split("."):
        target = getattr(target, part)
    if not isinstance(target, Application):
        raise TypeError(
            f"{import_path!r} resolves to {type(target).__name__}, expected a "
            f"bound Application (deployment.bind(...))"
        )
    return target


def deploy_config(config: Dict[str, Any] | str,
                  _blocking: bool = True) -> List[DeploymentHandle]:
    """Deploy every application in a config dict or YAML file path.

    Idempotent: redeploying reconciles live deployments toward the new
    config (scale up/down in place, no downtime)."""
    if isinstance(config, str):
        import yaml

        with open(config) as f:
            config = yaml.safe_load(f)
    apps = config.get("applications")
    if not isinstance(apps, list) or not apps:
        raise ValueError("config must have a non-empty 'applications' list")
    handles = []
    for app_cfg in apps:
        import_path = app_cfg.get("import_path")
        if not import_path:
            raise ValueError(f"application entry missing import_path: {app_cfg}")
        overrides: Dict[str, Dict[str, Any]] = {}
        for dep in app_cfg.get("deployments") or []:
            dep = dict(dep)
            dep_name = dep.pop("name", None)
            if not dep_name:
                raise ValueError("deployment override entries need a 'name'")
            unknown = set(dep) - _OVERRIDE_FIELDS
            if unknown:
                raise ValueError(
                    f"unknown deployment option(s) for {dep_name!r}: "
                    f"{sorted(unknown)}"
                )
            overrides[dep_name] = dep
        handles.append(
            run(
                _load_import_path(import_path),
                name=app_cfg.get("name", "default"),
                route_prefix=app_cfg.get("route_prefix", "/"),
                deployment_overrides=overrides or None,
                _blocking=_blocking,
            )
        )
    return handles
