"""Model multiplexing: many models per replica with LRU residency.

Reference: python/ray/serve/multiplex.py (_ModelMultiplexWrapper) +
api.py multiplexed:635 / get_multiplexed_model_id:773. The router
prefers replicas that already host the requested model id
(pow_2_scheduler multiplex ranking); the replica loads on miss and
evicts least-recently-used models beyond ``max_num_models_per_replica``.
"""
from __future__ import annotations

import asyncio
import functools
import inspect
from collections import OrderedDict
from typing import Callable, Optional


class _ModelMultiplexWrapper:
    def __init__(self, load_fn, self_arg, max_num_models: int):
        self._load_fn = load_fn
        self._self_arg = self_arg
        self._max = max_num_models
        self._models: "OrderedDict[str, object]" = OrderedDict()
        self._locks = {}

    async def load(self, model_id: str):
        if model_id in self._models:
            self._models.move_to_end(model_id)
            return self._models[model_id]
        lock = self._locks.setdefault(model_id, asyncio.Lock())
        async with lock:
            if model_id in self._models:
                self._models.move_to_end(model_id)
                return self._models[model_id]
            while len(self._models) >= self._max and self._models:
                old_id, old = self._models.popitem(last=False)
                if hasattr(old, "__del__"):
                    try:
                        old.__del__()
                    except Exception:  # noqa: BLE001
                        pass
            if self._self_arg is not None:
                result = self._load_fn(self._self_arg, model_id)
            else:
                result = self._load_fn(model_id)
            if inspect.isawaitable(result):
                result = await result
            self._models[model_id] = result
            self._push_model_ids()
            return result

    def model_ids(self):
        return list(self._models)

    def _push_model_ids(self):
        """Tell the controller which models live here so routers can
        rank replicas by residency."""
        try:
            from .. import get_actor
            from ._private.common import CONTROLLER_NAME
            from ._private.replica import get_replica_context

            ctx = get_replica_context()
            dep_id_str = f"{ctx.app_name}#{ctx.deployment}"
            get_actor(CONTROLLER_NAME).record_multiplexed_model_ids.remote(
                dep_id_str, ctx.replica_id, tuple(self._models)
            )
        except Exception:  # noqa: BLE001 - outside a replica (unit tests)
            pass


def multiplexed(
    _func: Optional[Callable] = None, *, max_num_models_per_replica: int = 3
):
    """Decorate a model-loading function/method; call it with a model id
    to get the (cached) model."""

    def wrap(func):
        params = list(inspect.signature(func).parameters)
        is_method = bool(params) and params[0] == "self"
        wrappers = {}

        if is_method:

            @functools.wraps(func)
            async def method_wrapper(self, model_id: str):
                w = wrappers.get(id(self))
                if w is None:
                    w = _ModelMultiplexWrapper(
                        func, self, max_num_models_per_replica
                    )
                    wrappers[id(self)] = w
                    _register_wrapper(self, w)
                return await w.load(model_id)

            return method_wrapper

        w = _ModelMultiplexWrapper(func, None, max_num_models_per_replica)

        @functools.wraps(func)
        async def func_wrapper(model_id: str):
            return await w.load(model_id)

        func_wrapper.__serve_multiplex_wrapper__ = w
        return func_wrapper

    if _func is not None:
        return wrap(_func)
    return wrap


def _register_wrapper(instance, wrapper):
    if not hasattr(instance, "__serve_multiplex_wrappers__"):
        try:
            instance.__serve_multiplex_wrappers__ = []
        except Exception:  # noqa: BLE001
            return
    instance.__serve_multiplex_wrappers__.append(wrapper)


def get_loaded_model_ids(callable_obj) -> list:
    out = []
    for w in getattr(callable_obj, "__serve_multiplex_wrappers__", []):
        out.extend(w.model_ids())
    return out


def get_multiplexed_model_id() -> str:
    """Inside a replica handling a request: the model id the caller
    asked for via handle.options(multiplexed_model_id=...)."""
    from ._private.replica import get_replica_context

    try:
        return get_replica_context().multiplexed_model_id
    except RuntimeError:
        return ""
