"""Long-poll config propagation (reference: serve/_private/long_poll.py
LongPollHost:173 / LongPollClient:64).

The host lives inside the controller. Clients (routers, proxies) call
``listen_for_change(snapshot_ids)`` — an async actor method that parks
until any watched key advances past the caller's snapshot id, then
returns the changed key→(snapshot_id, object) map. This turns config
distribution into O(changes), not O(polls).
"""
from __future__ import annotations

import asyncio
import threading
from typing import Any, Callable, Dict, Optional, Tuple

LISTEN_TIMEOUT_S = 30.0


class LongPollHost:
    def __init__(self):
        self._snapshot_ids: Dict[str, int] = {}
        self._objects: Dict[str, Any] = {}
        self._event = asyncio.Event()

    def notify_changed(self, updates: Dict[str, Any]) -> None:
        for key, obj in updates.items():
            self._snapshot_ids[key] = self._snapshot_ids.get(key, 0) + 1
            self._objects[key] = obj
        # Wake all parked listeners; each re-checks its own keys.
        self._event.set()
        self._event = asyncio.Event()

    def _changes_for(self, snapshot_ids: Dict[str, int]) -> Dict[str, Tuple[int, Any]]:
        out = {}
        for key, client_id in snapshot_ids.items():
            cur = self._snapshot_ids.get(key, 0)
            if cur > client_id and key in self._objects:
                out[key] = (cur, self._objects[key])
        return out

    async def listen_for_change(
        self, snapshot_ids: Dict[str, int]
    ) -> Dict[str, Tuple[int, Any]]:
        changes = self._changes_for(snapshot_ids)
        if changes:
            return changes
        event = self._event
        try:
            await asyncio.wait_for(event.wait(), timeout=LISTEN_TIMEOUT_S)
        except asyncio.TimeoutError:
            return {}
        return self._changes_for(snapshot_ids)


class LongPollClient:
    """Runs a poll loop on a daemon thread; invokes ``callbacks[key]``
    with the new object whenever a key changes."""

    def __init__(
        self,
        controller_handle,
        callbacks: Dict[str, Callable[[Any], None]],
    ):
        self._controller = controller_handle
        self._callbacks = callbacks
        self._snapshot_ids = {k: 0 for k in callbacks}
        self._stopped = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stopped.set()

    def _loop(self):
        from ... import get

        while not self._stopped.is_set():
            try:
                changes = get(
                    self._controller.listen_for_change.remote(self._snapshot_ids),
                    timeout=LISTEN_TIMEOUT_S + 10.0,
                )
            except Exception:
                if self._stopped.is_set():
                    return
                self._stopped.wait(0.5)
                continue
            for key, (snapshot_id, obj) in changes.items():
                self._snapshot_ids[key] = snapshot_id
                try:
                    self._callbacks[key](obj)
                except Exception:  # noqa: BLE001 - callbacks must not kill the loop
                    pass
