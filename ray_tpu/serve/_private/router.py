"""Handle-side router: assigns requests to replicas.

Reference: serve/_private/router.py (Router:312, assign_request:518) +
PowerOfTwoChoicesReplicaScheduler
(replica_scheduler/pow_2_scheduler.py:49): sample two candidate
replicas, pick the one with the lower queue length; rejection (replica
at max_ongoing_requests) triggers re-assignment with backoff.

The router keeps a local in-flight estimate per replica (incremented on
send, decremented on completion) so steady-state routing needs no probe
RPCs; the replica set itself arrives via long-poll from the controller.
"""
from __future__ import annotations

import asyncio
import random
import threading
import time
import uuid
from collections import defaultdict
from typing import Dict, List, Optional

from .common import (
    CONTROLLER_NAME,
    DeploymentID,
    LongPollKey,
    RequestMetadata,
    RunningReplicaInfo,
)
from .long_poll import LongPollClient
from .replica import RejectedError

ASSIGN_RETRY_BACKOFF_S = 0.025
METRICS_PUSH_INTERVAL_S = 0.5


class _ReplicaSet:
    def __init__(self):
        self.replicas: Dict[str, RunningReplicaInfo] = {}
        self.handles: Dict[str, object] = {}  # replica_id -> ActorHandle
        self.inflight: Dict[str, int] = defaultdict(int)
        self.changed = threading.Event()

    def update(self, infos: List[RunningReplicaInfo]):
        from ... import get_actor

        new = {}
        handles = {}
        for info in infos:
            new[info.replica_id] = info
            if info.replica_id in self.handles:
                handles[info.replica_id] = self.handles[info.replica_id]
            else:
                try:
                    handles[info.replica_id] = get_actor(info.actor_name)
                except ValueError:
                    continue
        self.replicas = new
        self.handles = handles
        # Drop drained counters for removed replicas so the estimate map
        # doesn't grow across redeployments. Replicas removed with
        # requests still in flight keep their count until it drains to 0
        # (deleting early would let the finally resurrect the key at -1).
        for rid in list(self.inflight):
            if rid not in new and self.inflight[rid] <= 0:
                del self.inflight[rid]
        self.changed.set()
        self.changed = threading.Event()


class PowerOfTwoChoicesReplicaScheduler:
    """Pick min-load of two random candidates; prefer replicas serving
    the request's multiplexed model id (reference pow_2_scheduler.py:49
    locality/multiplex ranking)."""

    def __init__(self, replica_set: _ReplicaSet):
        self._rs = replica_set

    def choose(self, meta: RequestMetadata) -> Optional[str]:
        rs = self._rs
        ids = list(rs.replicas)
        if not ids:
            return None
        if meta.multiplexed_model_id:
            owners = [
                rid
                for rid in ids
                if meta.multiplexed_model_id in rs.replicas[rid].multiplexed_model_ids
            ]
            if owners:
                ids = owners
        candidates = random.sample(ids, min(2, len(ids)))
        best = min(candidates, key=lambda rid: rs.inflight[rid])
        # Honor max_ongoing_requests with the local estimate; the replica
        # still enforces the hard cap via RejectedError.
        if rs.inflight[best] >= rs.replicas[best].max_ongoing_requests:
            return None
        return best


class Router:
    """One per (process, deployment). Owns a daemon asyncio loop so
    many requests are in flight concurrently."""

    def __init__(self, deployment_id: DeploymentID, controller_handle):
        self._dep_id = deployment_id
        self._controller = controller_handle
        self._replica_set = _ReplicaSet()
        self._scheduler = PowerOfTwoChoicesReplicaScheduler(self._replica_set)
        self._num_queued = 0
        self._queued_lock = threading.Lock()
        self._handle_id = uuid.uuid4().hex[:8]
        self._loop = asyncio.new_event_loop()
        threading.Thread(target=self._run_loop, daemon=True).start()
        self._long_poll = LongPollClient(
            controller_handle,
            {
                LongPollKey.running_replicas(deployment_id): self._replica_set.update,
            },
        )
        self._metrics_thread = threading.Thread(
            target=self._push_metrics_loop, daemon=True
        )
        self._metrics_thread.start()

    def _run_loop(self):
        asyncio.set_event_loop(self._loop)
        self._loop.run_forever()

    def shutdown(self):
        self._long_poll.stop()
        self._loop.call_soon_threadsafe(self._loop.stop)

    # ------------------------------------------------------------ public
    def assign_request(self, meta: RequestMetadata, args, kwargs):
        """Returns a concurrent.futures.Future with the final result
        (rejections retried transparently). Raises BackPressureError
        when max_queued_requests is exceeded (reference: router.py
        handle-side queue cap)."""
        # Count the request against the queue cap synchronously on the
        # caller thread — incrementing inside the coroutine would let a
        # burst of callers all pass the cap before the loop runs.
        with self._queued_lock:
            cap = self._max_queued()
            if cap >= 0 and self._num_queued >= cap:
                from ...exceptions import BackPressureError

                raise BackPressureError(
                    f"{self._dep_id}: {self._num_queued} queued requests "
                    f"(max_queued_requests={cap})"
                )
            self._num_queued += 1
        try:
            fut = asyncio.run_coroutine_threadsafe(
                self._assign_and_run(meta, args, kwargs), self._loop
            )
        except BaseException:
            with self._queued_lock:
                self._num_queued -= 1
            raise
        # Decrement on the future, not in the coroutine: a cancel before
        # the task's first step would skip the coroutine's finally.
        fut.add_done_callback(self._dec_queued)
        return fut

    def assign_request_streaming(self, meta: RequestMetadata, args, kwargs):
        """Streaming assignment: returns (async_value_generator, loop).
        The generator runs on the router loop and yields chunk VALUES;
        it carries the same admission semantics as the unary path — a
        replica that rejects (or dies) before producing anything is
        retried elsewhere, and the in-flight estimate covers the whole
        stream's lifetime (reference: router streaming calls ride the
        generator path with rejection retries)."""
        return self._stream_values(meta, args, kwargs), self._loop

    async def _stream_values(self, meta: RequestMetadata, args, kwargs):
        from .replica import RejectedError

        rs = self._replica_set
        args, kwargs = await _resolve_composed_args(args, kwargs)
        loop = asyncio.get_running_loop()
        while True:
            rid = self._scheduler.choose(meta)
            if rid is None:
                await asyncio.sleep(ASSIGN_RETRY_BACKOFF_S)
                continue
            handle = rs.handles.get(rid)
            if handle is None:
                await asyncio.sleep(ASSIGN_RETRY_BACKOFF_S)
                continue
            rs.inflight[rid] += 1
            yielded = False
            try:
                refgen = handle.handle_request_streaming.options(
                    num_returns="streaming"
                ).remote(meta, *args, **kwargs)
                async for ref in refgen:
                    value = await loop.run_in_executor(None, _get_one, ref)
                    yielded = True
                    yield value
                return
            except RejectedError:
                if yielded:
                    raise
                await asyncio.sleep(ASSIGN_RETRY_BACKOFF_S)
            except Exception as e:  # noqa: BLE001
                if yielded or not _is_actor_death(e):
                    raise
                rs.replicas.pop(rid, None)
                rs.handles.pop(rid, None)
            finally:
                rs.inflight[rid] -= 1

    def _dec_queued(self, _fut):
        with self._queued_lock:
            self._num_queued -= 1

    def _max_queued(self) -> int:
        for info in self._replica_set.replicas.values():
            return info.max_queued_requests
        return -1

    # ---------------------------------------------------------- internal
    async def _assign_and_run(self, meta: RequestMetadata, args, kwargs):
        rs = self._replica_set
        args, kwargs = await _resolve_composed_args(args, kwargs)
        while True:
            rid = self._scheduler.choose(meta)
            if rid is None:
                await asyncio.sleep(ASSIGN_RETRY_BACKOFF_S)
                continue
            handle = rs.handles.get(rid)
            if handle is None:
                await asyncio.sleep(ASSIGN_RETRY_BACKOFF_S)
                continue
            rs.inflight[rid] += 1
            try:
                ref = handle.handle_request.remote(meta, *args, **kwargs)
                return await ref
            except RejectedError:
                # Hard cap hit; try another replica.
                await asyncio.sleep(ASSIGN_RETRY_BACKOFF_S)
            except Exception as e:
                # Dead replica: drop it and retry until the controller
                # pushes a fresh set (reference: router retries on
                # ActorDiedError).
                if _is_actor_death(e):
                    rs.replicas.pop(rid, None)
                    rs.handles.pop(rid, None)
                    continue
                raise
            finally:
                rs.inflight[rid] -= 1

    def _push_metrics_loop(self):
        from ..._private.worker import is_initialized

        while True:
            # This daemon thread can outlive serve.shutdown() (handles
            # are plain objects, nothing joins it): pushing through a
            # dead session would auto-init a fresh one — exit instead.
            if not is_initialized():
                return
            try:
                self._controller.record_handle_metrics.remote(
                    str(self._dep_id), self._handle_id, self._num_queued, time.time()
                )
            except Exception:  # noqa: BLE001
                pass
            time.sleep(METRICS_PUSH_INTERVAL_S)


async def _resolve_composed_args(args, kwargs):
    """DeploymentResponses passed as arguments resolve on the router
    loop (never blocking the caller's thread — model composition,
    reference handle.py DeploymentResponse-to-ObjectRef conversion)."""
    import asyncio as _aio

    from ..handle import DeploymentResponse

    async def conv(v):
        if isinstance(v, DeploymentResponse):
            return await _aio.wrap_future(v._future)
        return v

    return (
        tuple([await conv(a) for a in args]),
        {k: await conv(v) for k, v in kwargs.items()},
    )


def _get_one(ref):
    import ray_tpu

    return ray_tpu.get(ref)


def _is_actor_death(e: BaseException) -> bool:
    from ...exceptions import ActorDiedError, ActorUnavailableError

    return isinstance(e, (ActorDiedError, ActorUnavailableError))


_routers: Dict[DeploymentID, Router] = {}
_routers_lock = threading.Lock()


def get_or_create_router(deployment_id: DeploymentID) -> Router:
    from ... import get_actor

    with _routers_lock:
        router = _routers.get(deployment_id)
        if router is None:
            router = Router(deployment_id, get_actor(CONTROLLER_NAME))
            _routers[deployment_id] = router
        return router


def shutdown_routers():
    with _routers_lock:
        for r in _routers.values():
            r.shutdown()
        _routers.clear()
