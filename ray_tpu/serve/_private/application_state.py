"""Application state: a named group of deployments with one ingress.

Reference: serve/_private/application_state.py (ApplicationState:117,
ApplicationStateManager:771).
"""
from __future__ import annotations

from typing import Dict, List, Optional

from .common import (
    ApplicationStatus,
    ApplicationStatusInfo,
    DeploymentID,
    DeploymentStatus,
    LongPollKey,
)


class ApplicationState:
    def __init__(self, name: str, route_prefix: Optional[str], ingress: str,
                 deployment_names: List[str], ingress_streaming: bool = False):
        self.name = name
        self.route_prefix = route_prefix
        self.ingress = ingress
        self.deployment_names = deployment_names
        # Ingress __call__ is a (sync/async) generator: the HTTP proxy
        # serves this app with chunked streaming responses.
        self.ingress_streaming = ingress_streaming
        self.status = ApplicationStatus.DEPLOYING
        self.message = ""
        self.deleting = False

    def deployment_ids(self) -> List[DeploymentID]:
        return [DeploymentID(n, self.name) for n in self.deployment_names]


class ApplicationStateManager:
    def __init__(self, deployment_state_manager, long_poll_host):
        self._dsm = deployment_state_manager
        self._long_poll = long_poll_host
        self._apps: Dict[str, ApplicationState] = {}
        self._last_routes: Optional[dict] = None

    def deploy(self, name, route_prefix, ingress, deployment_names,
               ingress_streaming: bool = False):
        # Remove deployments dropped by a redeploy.
        old = self._apps.get(name)
        if old:
            for dep in old.deployment_ids():
                if dep.name not in deployment_names:
                    self._dsm.delete(dep)
        self._apps[name] = ApplicationState(
            name, route_prefix, ingress, deployment_names, ingress_streaming
        )

    def delete(self, name: str):
        app = self._apps.get(name)
        if app is None:
            return
        app.deleting = True
        app.status = ApplicationStatus.DELETING
        for dep in app.deployment_ids():
            self._dsm.delete(dep)

    def update(self):
        for name in list(self._apps):
            app = self._apps[name]
            dep_statuses = {
                d.name: self._dsm.get(d).status_info
                for d in app.deployment_ids()
                if self._dsm.get(d) is not None
            }
            if app.deleting:
                if not dep_statuses:
                    del self._apps[name]
                continue
            if all(
                s.status == DeploymentStatus.HEALTHY for s in dep_statuses.values()
            ) and len(dep_statuses) == len(app.deployment_names):
                app.status = ApplicationStatus.RUNNING
            elif any(
                s.status == DeploymentStatus.UNHEALTHY for s in dep_statuses.values()
            ):
                app.status = ApplicationStatus.DEPLOY_FAILED
                app.message = "; ".join(
                    s.message for s in dep_statuses.values() if s.message
                )
            else:
                app.status = ApplicationStatus.DEPLOYING
        self._broadcast_routes()

    def _broadcast_routes(self):
        routes = {
            app.route_prefix: {
                "app_name": app.name,
                "ingress": app.ingress,
                "streaming": app.ingress_streaming,
            }
            for app in self._apps.values()
            if app.route_prefix and not app.deleting
        }
        if routes != self._last_routes:
            self._last_routes = routes
            self._long_poll.notify_changed({LongPollKey.ROUTE_TABLE: routes})
        apps = {
            app.name: {
                "app_name": app.name,
                "ingress": app.ingress,
                "streaming": app.ingress_streaming,
            }
            for app in self._apps.values()
            if not app.deleting
        }
        if apps != getattr(self, "_last_grpc_apps", None):
            self._last_grpc_apps = apps
            self._long_poll.notify_changed({LongPollKey.GRPC_APPS: apps})

    def status(self, name: str) -> Optional[ApplicationStatusInfo]:
        app = self._apps.get(name)
        if app is None:
            return None
        return ApplicationStatusInfo(
            status=app.status,
            message=app.message,
            deployments={
                d.name: self._dsm.get(d).status_info
                for d in app.deployment_ids()
                if self._dsm.get(d) is not None
            },
            route_prefix=app.route_prefix,
        )

    def statuses(self) -> Dict[str, ApplicationStatusInfo]:
        return {name: self.status(name) for name in self._apps}

    def get_app(self, name: str) -> Optional[ApplicationState]:
        return self._apps.get(name)
