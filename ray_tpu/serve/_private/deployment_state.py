"""Controller-side deployment reconciler.

Reference: serve/_private/deployment_state.py (DeploymentState:1226,
DeploymentReplica:211): each tick converges the live replica set toward
the target (count + version), performs health checks, and broadcasts
the running set to routers via the long-poll host.
"""
from __future__ import annotations

import hashlib
import pickle
import time
import uuid
from typing import Dict, List, Optional

from .common import (
    DeploymentID,
    DeploymentStatus,
    DeploymentStatusInfo,
    LongPollKey,
    ReplicaState,
    RunningReplicaInfo,
)


class DeploymentTarget:
    """Immutable desired state for one deployment."""

    def __init__(self, serialized_callable, init_args, init_kwargs, config):
        self.serialized_callable = serialized_callable
        self.init_args = init_args
        self.init_kwargs = init_kwargs
        self.config = config
        # Code version: changing the callable or init args requires
        # replica replacement; user_config changes reconfigure in place
        # (reference: DeploymentVersion).
        self.code_version = hashlib.sha1(
            serialized_callable + pickle.dumps((init_args, init_kwargs))
        ).hexdigest()[:12]
        self.target_num_replicas = config.initial_target_replicas
        self.deleting = False


class _Replica:
    def __init__(self, replica_id, actor_name, handle, version, user_config_hash):
        self.replica_id = replica_id
        self.actor_name = actor_name
        self.handle = handle
        self.version = version
        self.user_config_hash = user_config_hash
        self.state = ReplicaState.STARTING
        self.start_ref = None
        self.started_at = time.monotonic()
        self.health_ref = None
        self.last_health_check = time.monotonic()
        self.shutdown_ref = None
        self.multiplexed_model_ids: tuple = ()


def _user_config_hash(config) -> str:
    try:
        return hashlib.sha1(pickle.dumps(config.user_config)).hexdigest()[:12]
    except Exception:  # noqa: BLE001 - unpicklable configs still work in-place
        return uuid.uuid4().hex[:12]


class DeploymentState:
    START_TIMEOUT_S = 60.0

    def __init__(self, dep_id: DeploymentID, long_poll_host):
        self._id = dep_id
        self._long_poll = long_poll_host
        self._target: Optional[DeploymentTarget] = None
        self._replicas: List[_Replica] = []
        self._status = DeploymentStatusInfo(DeploymentStatus.UPDATING)
        self._last_broadcast: Optional[list] = None
        self._message = ""
        self._consecutive_start_failures = 0

    # ------------------------------------------------------------ target
    def set_target(self, target: DeploymentTarget):
        self._target = target
        self._status = DeploymentStatusInfo(DeploymentStatus.UPDATING)
        # A redeploy gets a fresh chance: clear the crash-loop latch.
        self._consecutive_start_failures = 0

    def set_target_num_replicas(self, n: int):
        if self._target and not self._target.deleting:
            self._target.target_num_replicas = n

    def delete(self):
        if self._target:
            self._target.deleting = True
            self._target.target_num_replicas = 0

    @property
    def target_num_replicas(self) -> int:
        return self._target.target_num_replicas if self._target else 0

    @property
    def is_deleted(self) -> bool:
        return bool(
            self._target and self._target.deleting and not self._replicas
        )

    # ------------------------------------------------------------ update
    def update(self) -> None:
        if self._target is None:
            return
        self._check_starting_replicas()
        self._check_stopping_replicas()
        self._reconfigure_or_replace_outdated()
        self._scale_to_target()
        self._run_health_checks()
        self._broadcast_running_replicas()
        self._refresh_status()

    # -------------------------------------------------------- transitions
    def _running(self) -> List[_Replica]:
        return [r for r in self._replicas if r.state == ReplicaState.RUNNING]

    def _check_starting_replicas(self):
        from ... import wait

        for r in self._replicas:
            if r.state != ReplicaState.STARTING or r.start_ref is None:
                continue
            ready, _ = wait([r.start_ref], timeout=0)
            if ready:
                try:
                    from ... import get

                    get(r.start_ref)
                    r.state = ReplicaState.RUNNING
                    self._consecutive_start_failures = 0
                except Exception as e:  # noqa: BLE001 - constructor failed
                    self._message = f"replica constructor failed: {e!r}"
                    self._consecutive_start_failures += 1
                    self._stop_replica(r, graceful=False)
            elif time.monotonic() - r.started_at > self.START_TIMEOUT_S:
                self._message = "replica start timed out"
                self._consecutive_start_failures += 1
                self._stop_replica(r, graceful=False)

    def _check_stopping_replicas(self):
        from ... import kill, wait

        still = []
        for r in self._replicas:
            if r.state != ReplicaState.STOPPING:
                still.append(r)
                continue
            done = r.shutdown_ref is None
            if not done:
                ready, _ = wait([r.shutdown_ref], timeout=0)
                done = bool(ready)
            if done:
                try:
                    kill(r.handle)
                except Exception:  # noqa: BLE001
                    pass
            else:
                still.append(r)
        self._replicas = still

    def _reconfigure_or_replace_outdated(self):
        """Surge rollout: old-version replicas keep serving until the
        new version has target_num_replicas RUNNING, then stop — a code
        redeploy never hits a zero-replica window (jax models can take
        seconds-to-minutes of compile in the new replicas)."""
        t = self._target
        cfg_hash = _user_config_hash(t.config)
        old = [
            r
            for r in self._replicas
            if r.state != ReplicaState.STOPPING and r.version != t.code_version
        ]
        if old:
            new_running = [
                r
                for r in self._running()
                if r.version == t.code_version
            ]
            if len(new_running) >= t.target_num_replicas:
                for r in old:
                    self._stop_replica(r, graceful=True)
        for r in self._replicas:
            if (
                r.state == ReplicaState.RUNNING
                and r.version == t.code_version
                and r.user_config_hash != cfg_hash
            ):
                r.handle.reconfigure.remote(t.config.user_config)
                r.user_config_hash = cfg_hash

    def _scale_to_target(self):
        t = self._target
        # Only new-version replicas count toward the target; old ones
        # are surge capacity handled above.
        alive = [
            r
            for r in self._replicas
            if r.state != ReplicaState.STOPPING and r.version == t.code_version
        ]
        delta = t.target_num_replicas - len(alive)
        if delta > 0:
            if self._consecutive_start_failures >= self.MAX_START_FAILURES:
                return  # crash loop — stop burning workers
            for _ in range(delta):
                self._start_replica()
        elif delta < 0:
            # Prefer stopping not-yet-running replicas.
            victims = sorted(
                alive, key=lambda r: r.state == ReplicaState.RUNNING
            )[: -delta]
            for r in victims:
                self._stop_replica(r, graceful=True)

    def _start_replica(self):
        from ... import remote

        from .replica import ReplicaActor

        t = self._target
        replica_id = f"{self._id}#{uuid.uuid4().hex[:8]}"
        actor_name = f"{self._id.actor_prefix()}#{replica_id[-8:]}"
        actor_cls = remote(ReplicaActor).options(
            name=actor_name,
            max_concurrency=t.config.max_ongoing_requests + 8,
            **t.config.ray_actor_options,
        )
        handle = actor_cls.remote(
            self._id.name,
            self._id.app_name,
            replica_id,
            t.serialized_callable,
            t.init_args,
            t.init_kwargs,
            pickle.dumps(t.config),
        )
        r = _Replica(
            replica_id,
            actor_name,
            handle,
            t.code_version,
            _user_config_hash(t.config),
        )
        r.start_ref = handle.ensure_started.remote()
        self._replicas.append(r)

    def _stop_replica(self, r: _Replica, graceful: bool):
        from ... import kill

        if r.state == ReplicaState.STOPPING:
            return
        if graceful and r.state == ReplicaState.RUNNING:
            r.state = ReplicaState.STOPPING
            try:
                r.shutdown_ref = r.handle.prepare_for_shutdown.remote()
            except Exception:  # noqa: BLE001
                r.shutdown_ref = None
        else:
            r.state = ReplicaState.STOPPING
            r.shutdown_ref = None
            try:
                kill(r.handle)
            except Exception:  # noqa: BLE001
                pass

    def _run_health_checks(self):
        from ... import wait

        t = self._target
        period = t.config.health_check_period_s
        now = time.monotonic()
        for r in self._running():
            if r.health_ref is not None:
                ready, _ = wait([r.health_ref], timeout=0)
                if ready:
                    try:
                        from ... import get

                        get(r.health_ref)
                        r.last_health_check = now
                        r.health_ref = None
                    except Exception:  # noqa: BLE001 - unhealthy
                        self._message = f"replica {r.replica_id} failed health check"
                        self._stop_replica(r, graceful=False)
                elif now - r.last_health_check > t.config.health_check_timeout_s:
                    self._message = f"replica {r.replica_id} health check timed out"
                    self._stop_replica(r, graceful=False)
            elif now - r.last_health_check > period:
                try:
                    r.health_ref = r.handle.check_health.remote()
                except Exception:  # noqa: BLE001
                    self._stop_replica(r, graceful=False)

    def record_multiplexed_model_ids(self, replica_id: str, model_ids: tuple):
        """Pushed by the replica's multiplex wrapper on model load/evict;
        the next broadcast carries residency to routers."""
        for r in self._replicas:
            if r.replica_id == replica_id:
                r.multiplexed_model_ids = tuple(model_ids)

    # ---------------------------------------------------------- broadcast
    def _broadcast_running_replicas(self):
        t = self._target
        running = self._running()
        # Key includes model residency so multiplex updates re-broadcast.
        key = [(r.replica_id, r.multiplexed_model_ids) for r in running]
        if key == self._last_broadcast:
            return
        self._last_broadcast = key
        infos = [
            RunningReplicaInfo(
                replica_id=r.replica_id,
                deployment_id=self._id,
                actor_name=r.actor_name,
                max_ongoing_requests=t.config.max_ongoing_requests,
                multiplexed_model_ids=r.multiplexed_model_ids,
                max_queued_requests=t.config.max_queued_requests,
            )
            for r in running
        ]
        self._long_poll.notify_changed(
            {LongPollKey.running_replicas(self._id): infos}
        )

    MAX_START_FAILURES = 3

    def _refresh_status(self):
        n_running = len(self._running())
        target = self.target_num_replicas
        if self._consecutive_start_failures >= self.MAX_START_FAILURES:
            # Crash loop: stop retrying and surface DEPLOY_FAILED.
            self._status = DeploymentStatusInfo(
                DeploymentStatus.UNHEALTHY, self._message,
                num_replicas=n_running,
            )
            return
        if n_running == target and all(
            r.state == ReplicaState.RUNNING
            for r in self._replicas
        ):
            self._status = DeploymentStatusInfo(
                DeploymentStatus.HEALTHY, num_replicas=n_running
            )
        elif n_running < target:
            self._status = DeploymentStatusInfo(
                DeploymentStatus.UPDATING, self._message, num_replicas=n_running
            )
        else:
            self._status = DeploymentStatusInfo(
                DeploymentStatus.DOWNSCALING, num_replicas=n_running
            )

    @property
    def status_info(self) -> DeploymentStatusInfo:
        return self._status


class DeploymentStateManager:
    def __init__(self, long_poll_host):
        self._long_poll = long_poll_host
        self._states: Dict[DeploymentID, DeploymentState] = {}

    def deploy(self, dep_id: DeploymentID, target: DeploymentTarget):
        state = self._states.get(dep_id)
        if state is None:
            state = DeploymentState(dep_id, self._long_poll)
            self._states[dep_id] = state
        state.set_target(target)

    def delete(self, dep_id: DeploymentID):
        if dep_id in self._states:
            self._states[dep_id].delete()

    def get(self, dep_id: DeploymentID) -> Optional[DeploymentState]:
        return self._states.get(dep_id)

    def update(self):
        for dep_id in list(self._states):
            state = self._states[dep_id]
            state.update()
            if state.is_deleted:
                del self._states[dep_id]

    def statuses(self) -> Dict[DeploymentID, DeploymentStatusInfo]:
        return {d: s.status_info for d, s in self._states.items()}
