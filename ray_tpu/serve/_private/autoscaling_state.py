"""Queue-length autoscaling (reference:
serve/_private/autoscaling_state.py AutoscalingStateManager:82 + default
policy serve/autoscaling_policy.py:85).

desired = ceil(total_requests / target_ongoing_requests) where
total_requests = mean over the look-back window of (sum of per-replica
ongoing) + (sum of per-handle queued). A scale decision is applied only
after it has persisted for upscale_delay_s / downscale_delay_s.
"""
from __future__ import annotations

import math
import time
from collections import defaultdict, deque
from typing import Deque, Dict, Optional, Tuple

from .common import DeploymentID


class _DeploymentAutoscaling:
    def __init__(self, config, current_target: int):
        self.config = config
        # (timestamp, value) series per source.
        self.replica_metrics: Dict[str, Deque[Tuple[float, float]]] = defaultdict(
            lambda: deque(maxlen=256)
        )
        self.handle_metrics: Dict[str, Deque[Tuple[float, float]]] = defaultdict(
            lambda: deque(maxlen=256)
        )
        self.target = current_target
        self._proposal: Optional[int] = None
        self._proposal_since: float = 0.0

    def record_replica(self, replica_id: str, ongoing: float, ts: float):
        self.replica_metrics[replica_id].append((ts, ongoing))

    def record_handle(self, handle_id: str, queued: float, ts: float):
        self.handle_metrics[handle_id].append((ts, queued))

    def _windowed_mean(self, series: Deque[Tuple[float, float]], now: float) -> float:
        lo = now - self.config.look_back_period_s
        vals = [v for (t, v) in series if t >= lo]
        return sum(vals) / len(vals) if vals else 0.0

    def _prune(self, now: float) -> None:
        """Drop series from replicas/handles gone longer than the
        look-back window (otherwise controller memory and per-tick work
        grow with replica churn forever)."""
        horizon = now - 2 * self.config.look_back_period_s
        for table in (self.replica_metrics, self.handle_metrics):
            dead = [
                k for k, s in table.items() if not s or s[-1][0] < horizon
            ]
            for k in dead:
                del table[k]

    def decide(self, now: Optional[float] = None) -> int:
        now = time.time() if now is None else now
        self._prune(now)
        cfg = self.config
        total = sum(
            self._windowed_mean(s, now) for s in self.replica_metrics.values()
        ) + sum(self._windowed_mean(s, now) for s in self.handle_metrics.values())
        raw = math.ceil(total / max(cfg.target_ongoing_requests, 1e-9))
        if raw > self.target:
            desired = self.target + max(
                1, math.ceil((raw - self.target) * cfg.upscaling_factor)
            )
            delay = cfg.upscale_delay_s
        elif raw < self.target:
            desired = self.target - max(
                1, math.ceil((self.target - raw) * cfg.downscaling_factor)
            )
            delay = cfg.downscale_delay_s
        else:
            self._proposal = None
            return self.target
        desired = cfg.bound(desired)
        if desired == self.target:
            self._proposal = None
            return self.target
        if self._proposal is None or (desired > self.target) != (
            self._proposal > self.target
        ):
            self._proposal = desired
            self._proposal_since = now
            return self.target
        # Same direction pending: apply once the delay has elapsed; take
        # the latest magnitude.
        if now - self._proposal_since >= delay:
            self.target = desired
            self._proposal = None
        else:
            self._proposal = desired
        return self.target


class AutoscalingStateManager:
    def __init__(self):
        self._states: Dict[DeploymentID, _DeploymentAutoscaling] = {}

    def register(self, dep_id: DeploymentID, config, current_target: int):
        state = self._states.get(dep_id)
        if state is None or state.config != config:
            state = _DeploymentAutoscaling(config, current_target)
            self._states[dep_id] = state

    def deregister(self, dep_id: DeploymentID):
        self._states.pop(dep_id, None)

    def record_replica(self, dep_id: DeploymentID, replica_id, ongoing, ts):
        if dep_id in self._states:
            self._states[dep_id].record_replica(replica_id, ongoing, ts)

    def record_handle(self, dep_id: DeploymentID, handle_id, queued, ts):
        if dep_id in self._states:
            self._states[dep_id].record_handle(handle_id, queued, ts)

    def get_decision(self, dep_id: DeploymentID) -> Optional[int]:
        state = self._states.get(dep_id)
        return state.decide() if state else None
