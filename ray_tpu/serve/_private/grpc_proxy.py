"""gRPC ingress proxy.

Reference: serve/_private/proxy.py:540 (gRPCProxy) — gRPC requests ride
the same route table + DeploymentHandle path as HTTP. Schema-free
transport: a generic handler accepts any ``/<app_name>/<method>`` (or
``/ray_tpu.serve.Serve/Call`` with app/method in metadata) unary call
whose request bytes are a pickled ``(args, kwargs)`` tuple and whose
response bytes are the pickled return value — no protoc codegen needed
for either side (the reference's RayServeAPIService plays the same
role for its generic entrypoints).
"""
from __future__ import annotations

import pickle
from typing import Dict, Optional

from ..handle import DeploymentHandle
from .common import LongPollKey


class _GenericHandler:
    def __init__(self, proxy: "GrpcProxyActor"):
        self._proxy = proxy

    def service(self, handler_call_details):
        import grpc

        method = handler_call_details.method  # "/pkg.Service/Method"
        md = dict(handler_call_details.invocation_metadata or ())

        async def unary(request_bytes, context):
            return await self._proxy.handle_call(
                method, md, request_bytes, context
            )

        return grpc.unary_unary_rpc_method_handler(
            unary,
            request_deserializer=None,  # raw bytes in
            response_serializer=None,  # raw bytes out
        )


class GrpcProxyActor:
    """One per cluster (next to the HTTP proxy)."""

    def __init__(self, host: str, port: int):
        self._host = host
        self._port = port
        self._apps: Dict[str, dict] = {}  # app_name -> route info
        self._handles: Dict[str, DeploymentHandle] = {}
        self._long_poll = None
        self._server = None

    async def ready(self) -> str:
        if self._server is not None:
            return f"{self._host}:{self._port}"
        import grpc.aio

        from ... import get_actor
        from .common import CONTROLLER_NAME
        from .long_poll import LongPollClient

        self._long_poll = LongPollClient(
            get_actor(CONTROLLER_NAME),
            {LongPollKey.GRPC_APPS: self._update_routes},
        )
        self._server = grpc.aio.server()
        self._server.add_generic_rpc_handlers((_GenericHandler(self),))
        self._port = self._server.add_insecure_port(
            f"{self._host}:{self._port}"
        )
        await self._server.start()
        return f"{self._host}:{self._port}"

    def _update_routes(self, routes: Dict[str, dict]):
        apps = {}
        handles = {}
        for prefix, info in routes.items():
            apps[info["app_name"]] = info
            handles[info["app_name"]] = DeploymentHandle(
                info["ingress"], info["app_name"]
            )
        self._apps = apps
        self._handles = handles

    async def handle_call(self, method: str, metadata, request_bytes: bytes,
                          context):
        import grpc

        # Routing: "/<app>/<call_method>", or metadata
        # ("application", "call-method") with any method path.
        app = metadata.get("application")
        call_method = metadata.get("call-method", "__call__")
        if app is None:
            parts = [p for p in method.split("/") if p]
            if len(parts) == 2 and parts[0] in self._handles:
                app, call_method = parts
        if app is None or app not in self._handles:
            await context.abort(
                grpc.StatusCode.NOT_FOUND,
                f"no serve application for rpc {method!r}",
            )
        try:
            args, kwargs = pickle.loads(request_bytes) if request_bytes else (
                (), {}
            )
        except Exception as e:  # noqa: BLE001
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"request is not a pickled (args, kwargs): {e}",
            )
        handle = self._handles[app]
        if call_method != "__call__":
            handle = handle.options(method_name=call_method)
        try:
            result = await handle.remote(*args, **kwargs)
        except Exception as e:  # noqa: BLE001
            await context.abort(
                grpc.StatusCode.INTERNAL, f"{type(e).__name__}: {e}"
            )
        return pickle.dumps(result)

    async def shutdown(self):
        if self._long_poll:
            self._long_poll.stop()
        if self._server:
            await self._server.stop(grace=1.0)
