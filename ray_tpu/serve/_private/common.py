"""Shared Serve types (reference: serve/_private/common.py)."""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

CONTROLLER_NAME = "SERVE_CONTROLLER"
PROXY_NAME_PREFIX = "SERVE_PROXY"
DEFAULT_APP_NAME = "default"


@dataclass(frozen=True)
class DeploymentID:
    name: str
    app_name: str = DEFAULT_APP_NAME

    def actor_prefix(self) -> str:
        return f"SERVE_REPLICA::{self.app_name}#{self.name}"

    def __str__(self):
        return f"{self.app_name}#{self.name}"


class ReplicaState(str, enum.Enum):
    STARTING = "STARTING"
    RUNNING = "RUNNING"
    STOPPING = "STOPPING"


class DeploymentStatus(str, enum.Enum):
    UPDATING = "UPDATING"
    HEALTHY = "HEALTHY"
    UNHEALTHY = "UNHEALTHY"
    UPSCALING = "UPSCALING"
    DOWNSCALING = "DOWNSCALING"


class ApplicationStatus(str, enum.Enum):
    DEPLOYING = "DEPLOYING"
    RUNNING = "RUNNING"
    DEPLOY_FAILED = "DEPLOY_FAILED"
    DELETING = "DELETING"
    NOT_STARTED = "NOT_STARTED"


@dataclass
class RequestMetadata:
    request_id: str
    call_method: str = "__call__"
    multiplexed_model_id: str = ""
    http_request: bool = False


@dataclass
class RunningReplicaInfo:
    """What routers need to know about a live replica (reference:
    serve/_private/common.py RunningReplicaInfo)."""

    replica_id: str
    deployment_id: DeploymentID
    actor_name: str
    max_ongoing_requests: int
    multiplexed_model_ids: tuple = ()
    max_queued_requests: int = -1


@dataclass
class DeploymentStatusInfo:
    status: DeploymentStatus
    message: str = ""
    num_replicas: int = 0


@dataclass
class ApplicationStatusInfo:
    status: ApplicationStatus
    message: str = ""
    deployments: Dict[str, DeploymentStatusInfo] = field(default_factory=dict)
    route_prefix: Optional[str] = None


# Long-poll namespace keys (reference: serve/_private/long_poll.py
# LongPollNamespace).
class LongPollKey:
    @staticmethod
    def running_replicas(dep_id: DeploymentID) -> str:
        return f"RUNNING_REPLICAS::{dep_id}"

    ROUTE_TABLE = "ROUTE_TABLE"
    # All apps keyed by name (gRPC routes by application, not prefix —
    # apps with route_prefix=None are still gRPC-reachable).
    GRPC_APPS = "GRPC_APPS"


@dataclass
class HTTPRequest:
    """Framework-native HTTP request passed to ingress deployments
    (the reference passes a starlette Request; aiohttp-backed here)."""

    method: str
    path: str
    query: Dict[str, str]
    headers: Dict[str, str]
    body: bytes

    def json(self):
        import json

        return json.loads(self.body.decode() or "null")

    def text(self) -> str:
        return self.body.decode()
