"""Replica actor: hosts one copy of a deployment's callable.

Reference: serve/_private/replica.py (ReplicaActor:231,
handle_request_with_rejection:487 — rejection-based admission control).
Requests arrive as ordinary actor tasks on the async event loop, so a
replica overlaps many in-flight requests; a jax model held by the
callable is compiled once per replica process.
"""
from __future__ import annotations

import asyncio
import contextvars
import inspect
import pickle
import time
from typing import Any, Dict, Optional, Tuple

from .common import DeploymentID, RequestMetadata

# Module-global so user code can reach its own replica context
# (reference: serve/api.py get_replica_context:140).
_replica_context: Optional["ReplicaContext"] = None

# Per-request (requests overlap on the async loop, so this must be a
# contextvar, not a field on the shared context).
_request_model_id: contextvars.ContextVar[str] = contextvars.ContextVar(
    "serve_multiplexed_model_id", default=""
)


class ReplicaContext:
    def __init__(self, deployment_id: DeploymentID, replica_id: str):
        self.deployment = deployment_id.name
        self.app_name = deployment_id.app_name
        self.replica_id = replica_id

    @property
    def multiplexed_model_id(self) -> str:
        return _request_model_id.get()


def get_replica_context() -> ReplicaContext:
    if _replica_context is None:
        raise RuntimeError(
            "get_replica_context() may only be called inside a Serve replica."
        )
    return _replica_context


class RejectedError(Exception):
    """Replica at max_ongoing_requests; router must retry elsewhere."""


class ReplicaActor:
    def __init__(
        self,
        deployment_name: str,
        app_name: str,
        replica_id: str,
        serialized_callable: bytes,
        init_args: tuple,
        init_kwargs: dict,
        config_blob: bytes,
    ):
        global _replica_context
        self._dep_id = DeploymentID(deployment_name, app_name)
        self._replica_id = replica_id
        self._config = pickle.loads(config_blob)
        _replica_context = ReplicaContext(self._dep_id, replica_id)

        func_or_class = pickle.loads(serialized_callable)
        if inspect.isclass(func_or_class):
            self._callable = func_or_class(*init_args, **init_kwargs)
        else:
            # Function deployment: the "callable" is the function itself.
            self._callable = func_or_class
        self._is_function = not inspect.isclass(func_or_class)
        self._num_ongoing = 0
        self._metrics_task: Optional[asyncio.Task] = None
        if self._config.user_config is not None:
            self._apply_user_config(self._config.user_config)

    # ------------------------------------------------------------ control
    async def ensure_started(self) -> str:
        """Awaited by the controller to confirm the replica constructed;
        also kicks off the autoscaling metrics pusher."""
        if self._metrics_task is None and self._config.autoscaling_config:
            self._metrics_task = asyncio.get_running_loop().create_task(
                self._push_metrics_loop()
            )
        return self._replica_id

    def _apply_user_config(self, user_config) -> None:
        if hasattr(self._callable, "reconfigure"):
            self._callable.reconfigure(user_config)

    async def reconfigure(self, user_config) -> None:
        self._config.user_config = user_config
        self._apply_user_config(user_config)

    async def check_health(self) -> bool:
        if hasattr(self._callable, "check_health"):
            out = self._callable.check_health()
            if inspect.isawaitable(out):
                await out
        return True

    async def prepare_for_shutdown(self) -> None:
        """Drain: wait for in-flight requests (graceful shutdown,
        reference replica.py perform_graceful_shutdown)."""
        deadline = time.monotonic() + self._config.graceful_shutdown_timeout_s
        while self._num_ongoing > 0 and time.monotonic() < deadline:
            await asyncio.sleep(0.02)
        if hasattr(self._callable, "__del__"):
            try:
                self._callable.__del__()
            except Exception:  # noqa: BLE001
                pass

    def get_num_ongoing_requests(self) -> int:
        return self._num_ongoing

    async def list_multiplexed_model_ids(self) -> Tuple[str, ...]:
        from ..multiplex import get_loaded_model_ids

        return tuple(get_loaded_model_ids(self._callable))

    # ------------------------------------------------------------ serving
    async def handle_request(self, meta: RequestMetadata, *args, **kwargs):
        """Rejection-based admission: over-capacity calls raise
        RejectedError so the router retries another replica instead of
        queueing here (reference replica.py:487)."""
        if self._num_ongoing >= self._config.max_ongoing_requests:
            raise RejectedError(self._replica_id)
        self._num_ongoing += 1
        try:
            _request_model_id.set(meta.multiplexed_model_id)
            target = self._resolve_method(meta.call_method)
            result = target(*args, **kwargs)
            if inspect.isawaitable(result):
                result = await result
            return result
        finally:
            self._num_ongoing -= 1

    async def handle_request_streaming(
        self, meta: RequestMetadata, *args, **kwargs
    ):
        """Streaming variant: an async generator the worker runtime
        drives as a ``num_returns="streaming"`` task — each yielded
        chunk seals as its own object and reaches the caller while the
        handler is still producing (reference: replica.py:471
        handle_request_streaming)."""
        if self._num_ongoing >= self._config.max_ongoing_requests:
            raise RejectedError(self._replica_id)
        self._num_ongoing += 1
        try:
            _request_model_id.set(meta.multiplexed_model_id)
            target = self._resolve_method(meta.call_method)
            result = target(*args, **kwargs)
            if inspect.isawaitable(result):
                result = await result
            if hasattr(result, "__aiter__"):
                async for item in result:
                    yield item
            elif inspect.isgenerator(result):
                for item in result:
                    yield item
            else:
                yield result
        finally:
            self._num_ongoing -= 1

    def _resolve_method(self, name: str):
        if self._is_function:
            return self._callable
        if name == "__call__":
            call = getattr(self._callable, "__call__", None)
            if call is None:
                raise AttributeError(
                    f"Deployment {self._dep_id} has no __call__ method"
                )
            return call
        return getattr(self._callable, name)

    # ------------------------------------------------------- autoscaling
    async def _push_metrics_loop(self):
        from ... import get_actor

        from .common import CONTROLLER_NAME

        interval = self._config.autoscaling_config.metrics_interval_s
        controller = get_actor(CONTROLLER_NAME)
        while True:
            try:
                controller.record_autoscaling_metrics.remote(
                    str(self._dep_id), self._replica_id, self._num_ongoing, time.time()
                )
            except Exception:  # noqa: BLE001
                pass
            await asyncio.sleep(interval)
