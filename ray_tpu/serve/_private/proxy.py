"""HTTP proxy actor.

Reference: serve/_private/proxy.py (ProxyActor:1130, HTTPProxy:761 —
uvicorn/starlette there; aiohttp here). The proxy keeps a route table
pushed from the controller via long-poll, resolves the longest matching
route prefix to an application's ingress deployment, and forwards the
request through a DeploymentHandle.
"""
from __future__ import annotations

import asyncio
import json
from typing import Dict, Optional

from ..handle import DeploymentHandle
from .common import HTTPRequest, LongPollKey


class ProxyActor:
    def __init__(self, host: str, port: int):
        self._host = host
        self._port = port
        self._routes: Dict[str, dict] = {}
        self._handles: Dict[str, DeploymentHandle] = {}
        self._long_poll = None
        self._runner = None

    async def ready(self) -> str:
        if self._runner is not None:  # idempotent under get_if_exists races
            return f"http://{self._host}:{self._port}"
        from aiohttp import web

        from ... import get_actor
        from .common import CONTROLLER_NAME
        from .long_poll import LongPollClient

        self._long_poll = LongPollClient(
            get_actor(CONTROLLER_NAME),
            {LongPollKey.ROUTE_TABLE: self._update_routes},
        )
        app = web.Application(client_max_size=256 * 1024 * 1024)
        app.router.add_route("*", "/{tail:.*}", self._handle)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self._host, self._port)
        await site.start()
        return f"http://{self._host}:{self._port}"

    def _update_routes(self, routes: Dict[str, dict]):
        self._routes = routes
        self._handles = {
            prefix: DeploymentHandle(
                info["ingress"], info["app_name"], _is_http=True
            )
            for prefix, info in routes.items()
        }

    def _match_route(self, path: str) -> Optional[str]:
        best = None
        for prefix in self._routes:
            norm = prefix.rstrip("/") or "/"
            if path == norm or path.startswith(norm + "/") or norm == "/":
                if best is None or len(norm) > len(best.rstrip("/") or "/"):
                    best = prefix
        return best

    async def _handle(self, request):
        from aiohttp import web

        if request.path == "/-/healthz":
            return web.Response(text="ok")
        if request.path == "/-/routes":
            return web.json_response(
                {p: i["app_name"] for p, i in self._routes.items()}
            )
        prefix = self._match_route(request.path)
        if prefix is None:
            return web.Response(status=404, text="no route")
        handle = self._handles[prefix]
        body = await request.read()
        req = HTTPRequest(
            method=request.method,
            path=request.path,
            query=dict(request.query),
            headers=dict(request.headers),
            body=body,
        )
        if self._routes[prefix].get("streaming"):
            return await self._handle_streaming(request, handle, req)
        try:
            result = await handle.remote(req)
        except Exception as e:  # noqa: BLE001 - surface as 500
            return web.Response(status=500, text=f"{type(e).__name__}: {e}")
        return _encode_response(web, result)

    async def _handle_streaming(self, request, handle, req):
        """Generator ingress: write each yielded chunk as it arrives —
        the client observes output while the handler is still running
        (reference: Serve token streaming over the generator path)."""
        from aiohttp import web

        gen = handle.options(stream=True).remote(req)
        resp = web.StreamResponse(
            status=200,
            headers={"Content-Type": "text/plain; charset=utf-8"},
        )
        await resp.prepare(request)
        try:
            async for chunk in gen:
                await resp.write(_encode_chunk(chunk))
        except Exception as e:  # noqa: BLE001 - stream already started
            await resp.write(
                f"\n[stream error] {type(e).__name__}: {e}".encode()
            )
        await resp.write_eof()
        return resp

    async def shutdown(self):
        if self._long_poll:
            self._long_poll.stop()
        if self._runner:
            await self._runner.cleanup()


def _encode_chunk(chunk) -> bytes:
    if isinstance(chunk, bytes):
        return chunk
    if isinstance(chunk, str):
        return chunk.encode()
    return (json.dumps(chunk) + "\n").encode()


def _encode_response(web, result):
    status = 200
    if isinstance(result, tuple) and len(result) == 2 and isinstance(result[0], int):
        status, result = result
    if isinstance(result, bytes):
        return web.Response(status=status, body=result)
    if isinstance(result, str):
        return web.Response(status=status, text=result)
    return web.Response(
        status=status,
        text=json.dumps(result),
        content_type="application/json",
    )
