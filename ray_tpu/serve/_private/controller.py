"""ServeController actor: the Serve control plane.

Reference: serve/_private/controller.py (ServeController:86). One named
async actor owns the application/deployment/autoscaling state machines
and a LongPollHost; ``run_control_loop`` reconciles every tick.
"""
from __future__ import annotations

import asyncio
import pickle
from typing import Any, Dict, Optional

from .application_state import ApplicationStateManager
from .autoscaling_state import AutoscalingStateManager
from .common import DeploymentID
from .deployment_state import DeploymentStateManager, DeploymentTarget
from .long_poll import LongPollHost

CONTROL_LOOP_INTERVAL_S = 0.05


class ServeController:
    def __init__(self, http_options_blob: bytes = b""):
        self._long_poll = LongPollHost()
        self._dsm = DeploymentStateManager(self._long_poll)
        self._asm = ApplicationStateManager(self._dsm, self._long_poll)
        self._autoscaling = AutoscalingStateManager()
        self._http_options = (
            pickle.loads(http_options_blob) if http_options_blob else None
        )
        self._shutdown = False
        self._loop_started = False

    # ------------------------------------------------------------- loop
    async def run_control_loop(self) -> None:
        if self._loop_started:
            return
        self._loop_started = True
        while not self._shutdown:
            try:
                self._dsm.update()
                self._asm.update()
                self._apply_autoscaling()
            except Exception:  # noqa: BLE001 - the loop must survive
                import traceback

                traceback.print_exc()
            await asyncio.sleep(CONTROL_LOOP_INTERVAL_S)

    def _apply_autoscaling(self):
        for dep_id, state in list(self._dsm._states.items()):
            target = state._target
            if target is None or target.config.autoscaling_config is None:
                continue
            self._autoscaling.register(
                dep_id, target.config.autoscaling_config, state.target_num_replicas
            )
            decision = self._autoscaling.get_decision(dep_id)
            if decision is not None and decision != state.target_num_replicas:
                state.set_target_num_replicas(decision)

    # ------------------------------------------------------------ deploy
    async def deploy_application(
        self, name: str, route_prefix: Optional[str], ingress: str,
        deployments_blob: bytes,
    ) -> None:
        """deployments_blob: pickled list of dicts with keys
        name/serialized_callable/init_args/init_kwargs/config."""
        infos = pickle.loads(deployments_blob)
        names = [d["name"] for d in infos]
        self._asm.deploy(
            name, route_prefix, ingress, names,
            ingress_streaming=_ingress_is_streaming(infos, ingress),
        )
        for d in infos:
            dep_id = DeploymentID(d["name"], name)
            self._dsm.deploy(
                dep_id,
                DeploymentTarget(
                    d["serialized_callable"],
                    d["init_args"],
                    d["init_kwargs"],
                    d["config"],
                ),
            )
            if d["config"].autoscaling_config is not None:
                self._autoscaling.register(
                    dep_id,
                    d["config"].autoscaling_config,
                    d["config"].initial_target_replicas,
                )
            else:
                self._autoscaling.deregister(dep_id)

    async def delete_application(self, name: str) -> None:
        self._asm.delete(name)

    async def get_app_statuses(self) -> Dict[str, Any]:
        return self._asm.statuses()

    async def get_app_info(self, name: str):
        app = self._asm.get_app(name)
        if app is None:
            return None
        return {
            "ingress": app.ingress,
            "route_prefix": app.route_prefix,
            "deployments": app.deployment_names,
        }

    async def graceful_shutdown(self) -> None:
        for name in list(self._asm._apps):
            self._asm.delete(name)
        # Wait for replicas to drain.
        for _ in range(200):
            self._dsm.update()
            self._asm.update()
            if not self._dsm._states:
                break
            await asyncio.sleep(0.05)
        self._shutdown = True

    # ----------------------------------------------------------- metrics
    async def record_autoscaling_metrics(
        self, dep_id_str: str, replica_id: str, ongoing: float, ts: float
    ) -> None:
        self._autoscaling.record_replica(
            _parse_dep_id(dep_id_str), replica_id, ongoing, ts
        )

    async def record_handle_metrics(
        self, dep_id_str: str, handle_id: str, queued: float, ts: float
    ) -> None:
        self._autoscaling.record_handle(
            _parse_dep_id(dep_id_str), handle_id, queued, ts
        )

    async def record_multiplexed_model_ids(
        self, dep_id_str: str, replica_id: str, model_ids: tuple
    ) -> None:
        state = self._dsm.get(_parse_dep_id(dep_id_str))
        if state is not None:
            state.record_multiplexed_model_ids(replica_id, model_ids)

    # ---------------------------------------------------------- longpoll
    async def listen_for_change(self, snapshot_ids: Dict[str, int]):
        return await self._long_poll.listen_for_change(snapshot_ids)

    async def get_http_options(self):
        return self._http_options


def _parse_dep_id(s: str) -> DeploymentID:
    app, _, name = s.partition("#")
    return DeploymentID(name, app)


def _ingress_is_streaming(infos, ingress_name: str) -> bool:
    """Deploy-time inspection: a generator (or async-generator) ingress
    handler means the HTTP proxy should stream chunked responses
    (reference: Serve streams when the app returns StreamingResponse)."""
    import inspect

    for d in infos:
        if d["name"] != ingress_name:
            continue
        try:
            c = pickle.loads(d["serialized_callable"])
        except Exception:  # noqa: BLE001 - env-specific callables
            return False
        target = c if not inspect.isclass(c) else getattr(c, "__call__", None)
        return bool(
            target is not None
            and (
                inspect.isgeneratorfunction(target)
                or inspect.isasyncgenfunction(target)
            )
        )
    return False
