"""DeploymentHandle: the composition API for calling deployments.

Reference: python/ray/serve/handle.py (_DeploymentHandleBase:104,
DeploymentResponse:456). A handle embeds a Router (power-of-two-choices
over live replicas); ``handle.method.remote(*args)`` returns a
DeploymentResponse that can be awaited, resolved with ``.result()``, or
passed directly as an argument to another handle call (model
composition).
"""
from __future__ import annotations

import concurrent.futures
import uuid
from typing import Any, Optional

from ._private.common import DeploymentID, RequestMetadata


class DeploymentResponse:
    def __init__(self, future: "concurrent.futures.Future"):
        self._future = future

    def result(self, timeout_s: Optional[float] = None) -> Any:
        return self._future.result(timeout=timeout_s)

    def cancel(self):
        self._future.cancel()

    def __await__(self):
        import asyncio

        return asyncio.wrap_future(self._future).__await__()


class _MethodProxy:
    def __init__(self, handle: "DeploymentHandle", method_name: str):
        self._handle = handle
        self._method = method_name

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._handle._remote(self._method, args, kwargs)


class DeploymentHandle:
    def __init__(
        self,
        deployment_name: str,
        app_name: str = "default",
        *,
        method_name: str = "__call__",
        multiplexed_model_id: str = "",
        _is_http: bool = False,
    ):
        self.deployment_id = DeploymentID(deployment_name, app_name)
        self._method_name = method_name
        self._multiplexed_model_id = multiplexed_model_id
        self._is_http = _is_http
        self._router = None

    # ------------------------------------------------------------ options
    def options(
        self,
        *,
        method_name: Optional[str] = None,
        multiplexed_model_id: Optional[str] = None,
    ) -> "DeploymentHandle":
        return DeploymentHandle(
            self.deployment_id.name,
            self.deployment_id.app_name,
            method_name=method_name or self._method_name,
            multiplexed_model_id=(
                multiplexed_model_id
                if multiplexed_model_id is not None
                else self._multiplexed_model_id
            ),
            _is_http=self._is_http,
        )

    def __getattr__(self, name: str) -> _MethodProxy:
        if name.startswith("_"):
            raise AttributeError(name)
        return _MethodProxy(self, name)

    # ------------------------------------------------------------- calls
    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._remote(self._method_name, args, kwargs)

    def _remote(self, method_name: str, args, kwargs) -> DeploymentResponse:
        from ._private.router import get_or_create_router

        if self._router is None:
            self._router = get_or_create_router(self.deployment_id)
        meta = RequestMetadata(
            request_id=uuid.uuid4().hex,
            call_method=method_name,
            multiplexed_model_id=self._multiplexed_model_id,
            http_request=self._is_http,
        )
        return DeploymentResponse(self._router.assign_request(meta, args, kwargs))

    def __repr__(self):
        return f"DeploymentHandle({self.deployment_id})"

    def __reduce__(self):
        return (
            _rebuild_handle,
            (
                self.deployment_id.name,
                self.deployment_id.app_name,
                self._method_name,
                self._multiplexed_model_id,
            ),
        )


def _rebuild_handle(name, app_name, method_name, multiplexed_model_id):
    return DeploymentHandle(
        name,
        app_name,
        method_name=method_name,
        multiplexed_model_id=multiplexed_model_id,
    )


