"""DeploymentHandle: the composition API for calling deployments.

Reference: python/ray/serve/handle.py (_DeploymentHandleBase:104,
DeploymentResponse:456). A handle embeds a Router (power-of-two-choices
over live replicas); ``handle.method.remote(*args)`` returns a
DeploymentResponse that can be awaited, resolved with ``.result()``, or
passed directly as an argument to another handle call (model
composition).
"""
from __future__ import annotations

import concurrent.futures
import uuid
from typing import Any, Optional

from ._private.common import DeploymentID, RequestMetadata


class DeploymentResponse:
    def __init__(self, future: "concurrent.futures.Future"):
        self._future = future

    def result(self, timeout_s: Optional[float] = None) -> Any:
        return self._future.result(timeout=timeout_s)

    def cancel(self):
        self._future.cancel()

    def __await__(self):
        import asyncio

        return asyncio.wrap_future(self._future).__await__()


class DeploymentResponseGenerator:
    """Iterator of streamed chunk VALUES from a stream=True handle call
    (reference: handle.py DeploymentResponseGenerator). The underlying
    async generator lives on the router's event loop (which owns
    replica choice, rejection retries, and in-flight accounting); sync
    and async iteration both bridge to it."""

    def __init__(self, agen, loop):
        self._agen = agen
        self._loop = loop

    def _pull(self) -> "concurrent.futures.Future":
        import asyncio

        async def nxt():
            try:
                return await self._agen.__anext__()
            except StopAsyncIteration:
                return _GEN_END

        return asyncio.run_coroutine_threadsafe(nxt(), self._loop)

    def __iter__(self):
        return self

    def __next__(self):
        item = self._pull().result()
        if item is _GEN_END:
            raise StopIteration
        return item

    def __aiter__(self):
        return self

    async def __anext__(self):
        import asyncio

        item = await asyncio.wrap_future(self._pull())
        if item is _GEN_END:
            raise StopAsyncIteration
        return item


_GEN_END = object()


class _MethodProxy:
    def __init__(self, handle: "DeploymentHandle", method_name: str):
        self._handle = handle
        self._method = method_name

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._handle._remote(self._method, args, kwargs)


class DeploymentHandle:
    def __init__(
        self,
        deployment_name: str,
        app_name: str = "default",
        *,
        method_name: str = "__call__",
        multiplexed_model_id: str = "",
        _is_http: bool = False,
        _stream: bool = False,
    ):
        self.deployment_id = DeploymentID(deployment_name, app_name)
        self._method_name = method_name
        self._multiplexed_model_id = multiplexed_model_id
        self._is_http = _is_http
        self._stream = _stream
        self._router = None

    # ------------------------------------------------------------ options
    def options(
        self,
        *,
        method_name: Optional[str] = None,
        multiplexed_model_id: Optional[str] = None,
        stream: Optional[bool] = None,
    ) -> "DeploymentHandle":
        return DeploymentHandle(
            self.deployment_id.name,
            self.deployment_id.app_name,
            method_name=method_name or self._method_name,
            multiplexed_model_id=(
                multiplexed_model_id
                if multiplexed_model_id is not None
                else self._multiplexed_model_id
            ),
            _is_http=self._is_http,
            _stream=self._stream if stream is None else stream,
        )

    def __getattr__(self, name: str) -> _MethodProxy:
        if name.startswith("_"):
            raise AttributeError(name)
        return _MethodProxy(self, name)

    # ------------------------------------------------------------- calls
    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._remote(self._method_name, args, kwargs)

    def _remote(self, method_name: str, args, kwargs):
        from ._private.router import get_or_create_router

        if self._router is None:
            self._router = get_or_create_router(self.deployment_id)
        meta = RequestMetadata(
            request_id=uuid.uuid4().hex,
            call_method=method_name,
            multiplexed_model_id=self._multiplexed_model_id,
            http_request=self._is_http,
        )
        if self._stream:
            agen, loop = self._router.assign_request_streaming(meta, args, kwargs)
            return DeploymentResponseGenerator(agen, loop)
        return DeploymentResponse(self._router.assign_request(meta, args, kwargs))

    def __repr__(self):
        return f"DeploymentHandle({self.deployment_id})"

    def __reduce__(self):
        return (
            _rebuild_handle,
            (
                self.deployment_id.name,
                self.deployment_id.app_name,
                self._method_name,
                self._multiplexed_model_id,
            ),
        )


def _rebuild_handle(name, app_name, method_name, multiplexed_model_id):
    return DeploymentHandle(
        name,
        app_name,
        method_name=method_name,
        multiplexed_model_id=multiplexed_model_id,
    )


