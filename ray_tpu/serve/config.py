"""Serve configuration dataclasses.

Reference: python/ray/serve/config.py (AutoscalingConfig :33,
HTTPOptions :233) — pydantic there; plain dataclasses here to stay
dependency-light.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class AutoscalingConfig:
    """Queue-length-based autoscaling (reference default policy:
    serve/autoscaling_policy.py:85 — desired = total_requests /
    target_ongoing_requests, smoothed and delay-gated)."""

    min_replicas: int = 1
    max_replicas: int = 1
    target_ongoing_requests: float = 2.0
    # Seconds a scaling decision must persist before it is applied.
    upscale_delay_s: float = 3.0
    downscale_delay_s: float = 30.0
    # Multiplicative smoothing on the size of each scaling move.
    upscaling_factor: float = 1.0
    downscaling_factor: float = 1.0
    # How often replicas/handles push metrics and how much history the
    # controller averages over.
    metrics_interval_s: float = 0.5
    look_back_period_s: float = 5.0
    initial_replicas: Optional[int] = None

    def bound(self, n: int) -> int:
        return max(self.min_replicas, min(self.max_replicas, n))


@dataclass
class HTTPOptions:
    host: str = "127.0.0.1"
    port: int = 8000
    root_path: str = ""


@dataclass
class GRPCOptions:
    """gRPC ingress (reference: serve gRPCOptions — grpc_servicer_
    functions there; schema-free generic service here)."""

    host: str = "127.0.0.1"
    port: int = 9000


@dataclass
class DeploymentConfig:
    """Per-deployment runtime knobs (reference:
    serve/_private/config.py DeploymentConfig)."""

    num_replicas: int = 1
    max_ongoing_requests: int = 100
    max_queued_requests: int = -1
    user_config: Any = None
    autoscaling_config: Optional[AutoscalingConfig] = None
    health_check_period_s: float = 2.0
    health_check_timeout_s: float = 30.0
    graceful_shutdown_timeout_s: float = 5.0
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)

    @property
    def initial_target_replicas(self) -> int:
        if self.autoscaling_config is not None:
            ac = self.autoscaling_config
            if ac.initial_replicas is not None:
                return ac.bound(ac.initial_replicas)
            return ac.min_replicas
        return self.num_replicas
