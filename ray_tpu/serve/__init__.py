"""ray_tpu.serve: scalable model serving on the actor substrate.

Architecture mirrors the reference (python/ray/serve — see SURVEY.md
§3.5): a ServeController actor owns application/deployment state and
reconciles replica actors toward the target; per-node ProxyActors serve
HTTP (aiohttp here, uvicorn/starlette in the reference); handles embed a
Router using power-of-two-choices replica scheduling
(serve/_private/replica_scheduler/pow_2_scheduler.py:49); config is
pushed via a long-poll host (serve/_private/long_poll.py:173).

TPU-native notes: replicas are ordinary ray_tpu actors, so a deployment
can hold a jitted jax model (compiled once per replica process) and
batched requests ride the MXU via `@serve.batch`.
"""
from __future__ import annotations

from .api import (  # noqa: F401
    Application,
    Deployment,
    delete,
    deployment,
    get_app_handle,
    get_deployment_handle,
    get_multiplexed_model_id,
    get_replica_context,
    ingress,
    multiplexed,
    run,
    shutdown,
    start,
    status,
)
from .batching import batch  # noqa: F401
from .config import AutoscalingConfig, GRPCOptions, HTTPOptions  # noqa: F401
from .handle import (  # noqa: F401
    DeploymentHandle,
    DeploymentResponse,
    DeploymentResponseGenerator,
)
from .schema import deploy_config  # noqa: F401

__all__ = [
    "Application",
    "AutoscalingConfig",
    "Deployment",
    "DeploymentHandle",
    "DeploymentResponse",
    "DeploymentResponseGenerator",
    "deploy_config",
    "HTTPOptions",
    "GRPCOptions",
    "batch",
    "delete",
    "deployment",
    "get_app_handle",
    "get_deployment_handle",
    "get_multiplexed_model_id",
    "get_replica_context",
    "ingress",
    "multiplexed",
    "run",
    "shutdown",
    "start",
    "status",
]

from ray_tpu._private import usage_stats as _usage

_usage.record_library_usage("serve")
