from .scripts.cli import main

main()
