"""ray_tpu.train: distributed training on TPU meshes.

Reference: python/ray/train — trainers, session contract,
checkpointing. See trainer.py for the architecture mapping.
"""
from .checkpoint import AsyncCheckpointer, Checkpoint, load_pytree, save_pytree  # noqa: F401
from .config import (  # noqa: F401
    CheckpointConfig,
    FailureConfig,
    Result,
    RunConfig,
    ScalingConfig,
)
from .session import get_context, report  # noqa: F401
from .trainer import JaxTrainer, get_checkpoint  # noqa: F401

from ray_tpu._private import usage_stats as _usage

_usage.record_library_usage("train")
