"""JaxTrainer: gang-scheduled SPMD training over actor worker groups.

Reference call stack (SURVEY.md §3.3): TorchTrainer.fit →
BackendExecutor + WorkerGroup actors + per-worker _TrainSession with a
report queue → TrainingIterator drains epoch results. This trainer
keeps that architecture — N worker actors gang-placed via a placement
group, session report contract, checkpoint persistence, group restart
on failure (FailureConfig) — with the torch/NCCL backend replaced by
the JAX model: each worker is one TPU host of a slice; worker 0's
address seeds `jax.distributed.initialize` (coordinator brokered
through the control plane KV, replacing the reference's
NCCLUniqueIDStore actor — util/collective/util.py:9); the mesh from
ScalingConfig spans all hosts' devices and XLA compiles the
collectives.

Single-worker mode (num_workers=1) drives the whole local mesh in one
process — the bench path on one host.
"""
from __future__ import annotations

import os
import threading
import time
import traceback
from typing import Any, Callable, Dict, Optional

import ray_tpu
from ..exceptions import RayActorError
from ..util.placement_group import placement_group, remove_placement_group
from ..util.scheduling_strategies import PlacementGroupSchedulingStrategy
from .checkpoint import Checkpoint
from .config import Result, RunConfig, ScalingConfig
from .session import TrainContext, get_session, init_session


class TrainWorker:
    """Actor wrapping one training process (reference:
    RayTrainWorker — train/_internal/worker_group.py)."""

    def __init__(self, rank: int, world_size: int, experiment_name: str,
                 storage_path: Optional[str], use_jax_distributed: bool = False,
                 num_processes: Optional[int] = None,
                 rendezvous_token: str = ""):
        self.rank = rank
        self.world_size = world_size
        self.session = init_session(
            TrainContext(
                world_rank=rank,
                world_size=world_size,
                local_rank=rank,
                node_rank=rank,
                experiment_name=experiment_name,
                storage_path=storage_path,
            )
        )
        self._thread: Optional[threading.Thread] = None
        if use_jax_distributed and world_size > 1:
            # Multi-host: join the jax.distributed cluster so all hosts
            # see the global device set. Rank 0 binds the coordinator and
            # publishes its address through the GCS KV; other ranks poll
            # for it (reference: coordinator rendezvous via the named
            # NCCLUniqueIDStore actor, util/collective/util.py:9).
            coordinator = self._rendezvous(
                f"{experiment_name}/{rendezvous_token}"
            )
            import jax

            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=num_processes or world_size,
                process_id=rank,
            )

    def _rendezvous(self, rendezvous_id: str) -> str:
        """rendezvous_id is unique per fit attempt (the driver mints a
        fresh token for every _fit_once) so a group restart can never
        read the previous attempt's dead coordinator address."""
        from .._private import transport
        from .._private.worker import global_client

        client = global_client()
        key = f"train_coordinator/{rendezvous_id}".encode()
        if self.rank == 0:
            import socket

            s = socket.socket()
            s.bind(("", 0))
            port = s.getsockname()[1]
            s.close()
            addr = f"{transport.node_ip()}:{port}"
            client.kv_put(key, addr.encode())
            return addr
        deadline = time.time() + 60
        while time.time() < deadline:
            val = client.kv_get(key)
            if val:
                return val.decode()
            time.sleep(0.1)
        raise TimeoutError("jax.distributed coordinator address never published")

    def run(self, train_loop: Callable, config: Dict[str, Any],
            latest_checkpoint: Optional[str] = None) -> bool:
        """Start the user loop in a background thread; results stream
        through next_result()."""
        self.session.context.latest_checkpoint = (
            Checkpoint(latest_checkpoint) if latest_checkpoint else None
        )

        def runner():
            try:
                # The user loop may take (config) or no args (reference:
                # train_loop_per_worker signature detection).
                import inspect

                if len(inspect.signature(train_loop).parameters) >= 1:
                    train_loop(config or {})
                else:
                    train_loop()
                self.session.finish()
            except BaseException as e:  # noqa: BLE001
                traceback.print_exc()
                self.session.finish(e)

        self._thread = threading.Thread(target=runner, daemon=True)
        self._thread.start()
        return True

    def next_result(self):
        kind, metrics, checkpoint = self.session.next_result()
        if kind == "done":
            err = self.session.error
            if err is not None:
                raise err if isinstance(err, Exception) else RuntimeError(str(err))
            return ("done", None, None)
        # Checkpoints are directories on shared storage; ship the path.
        ckpt_path = checkpoint.path if isinstance(checkpoint, Checkpoint) else checkpoint
        return (kind, metrics, ckpt_path)

    def ping(self):
        return self.rank


class JaxTrainer:
    """Reference: train/data_parallel_trainer.py:25 DataParallelTrainer;
    fit() contract from base_trainer.py:567."""

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
    ):
        self._train_loop = train_loop_per_worker
        self._config = train_loop_config
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}

    # ------------------------------------------------------------------ fit

    def fit(self) -> Result:
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        name = self.run_config.name or f"JaxTrainer_{int(time.time())}"
        storage = self.run_config.storage_path or os.path.join(
            "/tmp/ray_tpu_results", name
        )
        os.makedirs(storage, exist_ok=True)
        max_failures = self.run_config.failure_config.max_failures
        attempt = 0
        latest_ckpt: Optional[str] = None
        while True:
            try:
                return self._fit_once(name, storage, latest_ckpt)
            except RayActorError as e:
                attempt += 1
                if max_failures >= 0 and attempt > max_failures:
                    return Result(
                        metrics=None, checkpoint=None, error=e, path=storage
                    )
                latest_ckpt = self._latest_checkpoint_path(storage)

    def _latest_checkpoint_path(self, storage: str) -> Optional[str]:
        cands = sorted(
            (d for d in os.listdir(storage) if d.startswith("checkpoint_")),
            key=lambda d: int(d.split("_")[-1]),
        )
        return os.path.join(storage, cands[-1]) if cands else None

    def _fit_once(self, name: str, storage: str, latest_ckpt: Optional[str]) -> Result:
        sc = self.scaling_config
        n = sc.num_workers
        pg = placement_group(
            [sc.worker_resources() for _ in range(n)],
            strategy=sc.placement_strategy,
        )
        workers = []
        try:
            import secrets

            rdv_token = secrets.token_hex(4)
            worker_cls = ray_tpu.remote(TrainWorker)
            for rank in range(n):
                workers.append(
                    worker_cls.options(
                        scheduling_strategy=PlacementGroupSchedulingStrategy(
                            placement_group=pg,
                            placement_group_bundle_index=rank,
                        ),
                        max_concurrency=2,
                    ).remote(
                        rank, n, name, storage, sc.use_jax_distributed,
                        None, rdv_token,
                    )
                )
            ray_tpu.get([w.ping.remote() for w in workers], timeout=120)
            cfg = self._config
            if self.datasets:
                cfg = dict(cfg or {})
                cfg["__datasets__"] = self.datasets
            ray_tpu.get(
                [w.run.remote(self._train_loop, cfg, latest_ckpt) for w in workers],
                timeout=120,
            )
            history = []
            final_metrics = None
            checkpoint = None
            iteration = 0
            while True:
                results = ray_tpu.get(
                    [w.next_result.remote() for w in workers]
                )
                kinds = {r[0] for r in results}
                if "done" in kinds:
                    break
                iteration += 1
                rank0_kind, metrics, ckpt_path = results[0]
                final_metrics = metrics
                history.append(metrics)
                if ckpt_path:
                    persisted = os.path.join(storage, f"checkpoint_{iteration:06d}")
                    if os.path.abspath(ckpt_path) != persisted:
                        import shutil

                        shutil.copytree(ckpt_path, persisted, dirs_exist_ok=True)
                    checkpoint = Checkpoint(persisted)
                    self._prune_checkpoints(storage)
            return Result(
                metrics=final_metrics,
                checkpoint=checkpoint,
                error=None,
                path=storage,
                metrics_history=history,
            )
        finally:
            for w in workers:
                try:
                    ray_tpu.kill(w)
                except Exception:
                    pass
            try:
                remove_placement_group(pg)
            except Exception:
                pass

    def _prune_checkpoints(self, storage: str):
        keep = self.run_config.checkpoint_config.num_to_keep
        if not keep:
            return
        cands = sorted(
            (d for d in os.listdir(storage) if d.startswith("checkpoint_")),
            key=lambda d: int(d.split("_")[-1]),
        )
        import shutil

        for d in cands[:-keep]:
            shutil.rmtree(os.path.join(storage, d), ignore_errors=True)


def get_checkpoint() -> Optional[Checkpoint]:
    """Resume checkpoint for the current session (reference:
    train.get_checkpoint)."""
    s = get_session()
    if s is None:
        return None
    return getattr(s.context, "latest_checkpoint", None)
