"""Train configuration dataclasses.

Reference: air/config.py — ScalingConfig (:~200), RunConfig,
FailureConfig (:395), CheckpointConfig. The TPU ScalingConfig carries a
MeshSpec: where the reference scales by `num_workers` GPU processes
under NCCL, a TPU job is `num_workers` host processes jointly driving
one GSPMD mesh (axes dp/fsdp/seq/tp/ep) — the mesh IS the parallelism
declaration.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ..parallel.mesh import MeshSpec


@dataclass
class ScalingConfig:
    num_workers: int = 1
    use_tpu: bool = False
    resources_per_worker: Dict[str, float] = field(default_factory=dict)
    mesh: Optional[MeshSpec] = None
    placement_strategy: str = "PACK"
    #: Form one jax.distributed cluster across the worker group so every
    #: host sees the global device set (multi-host SPMD). Rank 0 brokers
    #: the coordinator address through the GCS KV (replaces the
    #: reference's NCCLUniqueIDStore actor — util/collective/util.py:9).
    use_jax_distributed: bool = False

    def worker_resources(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker)
        if not res:
            res = {"TPU": 1.0} if self.use_tpu else {"CPU": 1.0}
        return res


@dataclass
class FailureConfig:
    max_failures: int = 0
    # Separate budget for actor-loss (infra) failures — a preempted or
    # OOM-killed trial actor restarts from its latest checkpoint
    # without consuming max_failures (user-code error) budget.
    infra_retries: int = 3


@dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_frequency: int = 0


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    #: Tune stop criteria, e.g. {"training_iteration": 10} (reference:
    #: air RunConfig.stop).
    stop: Optional[Dict[str, Any]] = None


@dataclass
class Result:
    """Reference: air/result.py."""

    metrics: Optional[Dict[str, Any]]
    checkpoint: Optional[Any]
    error: Optional[BaseException]
    path: Optional[str]
    metrics_history: list = field(default_factory=list)
    config: Optional[Dict[str, Any]] = None
