"""Per-worker training session.

Reference: train/_internal/session.py — _TrainSession :110, report()
:402. The worker's train loop calls `ray_tpu.train.report(metrics,
checkpoint=...)`; results flow through a queue the trainer drains,
epoch-synchronized across the worker group.
"""
from __future__ import annotations

import queue
import threading
from dataclasses import dataclass
from typing import Any, Dict, Optional

_session_lock = threading.Lock()
_session: Optional["TrainSession"] = None


@dataclass
class TrainContext:
    world_rank: int
    world_size: int
    local_rank: int
    node_rank: int
    experiment_name: str
    storage_path: Optional[str]


class TrainSession:
    def __init__(self, context: TrainContext):
        self.context = context
        self.result_queue: "queue.Queue" = queue.Queue()
        self.finished = threading.Event()
        self.error: Optional[BaseException] = None

    def report(self, metrics: Dict[str, Any], checkpoint=None):
        self.result_queue.put(("report", metrics, checkpoint))

    def finish(self, error: Optional[BaseException] = None):
        self.error = error
        self.finished.set()
        self.result_queue.put(("done", None, None))

    def next_result(self, timeout: Optional[float] = None):
        return self.result_queue.get(timeout=timeout)


def init_session(context: TrainContext) -> TrainSession:
    global _session
    with _session_lock:
        _session = TrainSession(context)
        return _session


def get_session() -> Optional[TrainSession]:
    return _session


def report(metrics: Dict[str, Any], *, checkpoint=None) -> None:
    """Reference: ray.train.report — every worker must call it the same
    number of times; rank-0's checkpoint is persisted."""
    s = get_session()
    if s is None:
        raise RuntimeError("report() called outside a train session")
    s.report(metrics, checkpoint)


def get_context() -> TrainContext:
    s = get_session()
    if s is None:
        raise RuntimeError("get_context() called outside a train session")
    return s.context
