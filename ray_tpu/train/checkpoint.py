"""Checkpoints: directory contract + orbax sharded pytree persistence.

Reference: train/_checkpoint.py:56 (Checkpoint = directory + fs handle)
and the TPU guidance in SURVEY.md §5: orbax-style async multi-host
checkpoint of sharded arrays, keeping the report(metrics, checkpoint)
contract.
"""
from __future__ import annotations

import os
import shutil
import tempfile
from typing import Any, Optional


class Checkpoint:
    """A directory of checkpoint data (reference: train.Checkpoint)."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def as_directory(self) -> str:
        return self.path

    def to_directory(self, dest: Optional[str] = None) -> str:
        dest = dest or tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
        if os.path.abspath(dest) != self.path:
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    def __repr__(self):
        return f"Checkpoint({self.path})"

    def __reduce__(self):
        return (Checkpoint, (self.path,))


def save_pytree(path: str, tree: Any) -> None:
    """Synchronous sharded save via orbax (multi-host safe: every process
    writes its addressable shards)."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    if os.path.exists(path):
        shutil.rmtree(path)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, tree)


def load_pytree(path: str, abstract_tree: Any = None) -> Any:
    """Restore; pass an abstract tree (jax.ShapeDtypeStruct leaves with
    shardings) to restore sharded onto a mesh."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        if abstract_tree is None:
            return ckptr.restore(os.path.abspath(path))
        return ckptr.restore(os.path.abspath(path), abstract_tree)


class AsyncCheckpointer:
    """Async sharded checkpointing: device->host copy happens at save()
    call; serialization proceeds in background threads (orbax
    AsyncCheckpointer), keeping the TPU busy (SURVEY.md §5 checkpoint/
    resume TPU equivalent)."""

    def __init__(self):
        import orbax.checkpoint as ocp

        self._ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())

    def save(self, path: str, tree: Any) -> None:
        path = os.path.abspath(path)
        if os.path.exists(path):
            shutil.rmtree(path)
        import orbax.checkpoint as ocp

        self._ckptr.save(path, args=ocp.args.StandardSave(tree))

    def wait(self) -> None:
        self._ckptr.wait_until_finished()

    def close(self) -> None:
        self.wait()
        self._ckptr.close()
