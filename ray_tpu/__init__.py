"""ray_tpu: a TPU-native distributed compute framework.

Tasks, actors, a shared-memory object store, resource scheduling and
placement groups as the host-side substrate (the reference architecture
of wallies/ray, rebuilt — see SURVEY.md), with jax/XLA/pjit/pallas as
the accelerator path: SPMD programs over device meshes, in-graph XLA
collectives over ICI, pallas kernels for long-context attention.

Public API mirrors the reference's `ray` package:

    import ray_tpu

    ray_tpu.init()

    @ray_tpu.remote
    def f(x):
        return x * 2

    ray_tpu.get(f.remote(2))  # 4
"""
from __future__ import annotations

import inspect as _inspect

# Lock-order witness (RAY_TPU_lock_witness=1): stdlib-only module,
# installed BEFORE the runtime imports below so the module-level locks
# they create (events recorder lock, fastpath/native-store lib locks,
# ...) are witnessed too. No-op unless the env opt-in is set; every
# process that imports ray_tpu — driver, head, raylet, zygote, worker
# — passes through here first, so one inherited env var arms the
# whole tree with one shared enabled() predicate.
from ._private import lock_witness as _lock_witness

_lock_witness.maybe_install()

from ._private.worker import (  # noqa: F401
    available_resources,
    client_server_address,
    cluster_resources,
    drain_node,
    free,
    get,
    get_actor,
    init,
    is_initialized,
    kill,
    nodes,
    put,
    shutdown,
    wait,
)
from ._private.state import timeline  # noqa: F401
from .actor import ActorClass, ActorHandle  # noqa: F401
from .object_ref import ObjectRef, ObjectRefGenerator  # noqa: F401
from .remote_function import RemoteFunction  # noqa: F401
from . import exceptions  # noqa: F401

__version__ = "0.1.0"


def remote(*args, **kwargs):
    """Turn a function into a remote task or a class into an actor class.

    Usable bare (``@remote``) or with options
    (``@remote(num_cpus=2, num_tpus=1)``) — reference:
    _private/worker.py:132-376 overloads.
    """

    def _make(target):
        if _inspect.isclass(target):
            return ActorClass(target, **kwargs)
        if callable(target):
            return RemoteFunction(target, **kwargs)
        raise TypeError(f"@remote target must be a function or class: {target}")

    if len(args) == 1 and not kwargs and (callable(args[0]) or _inspect.isclass(args[0])):
        return _make(args[0])
    if args:
        raise TypeError("@remote options must be keyword arguments")
    return _make


def method(**kwargs):
    """Decorator for actor methods carrying default options
    (reference: ray.method)."""

    def deco(fn):
        fn.__ray_method_options__ = kwargs
        return fn

    return deco
