"""@remote functions (reference: python/ray/remote_function.py —
RemoteFunction._remote :266)."""
from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Union

from ._private import submit as _submit
from ._private.ids import PlacementGroupID, TaskID, fast_unique_bytes
from ._private.task_spec import TaskSpec
from ._private.worker import global_client
from .object_ref import ObjectRef

_VALID_OPTIONS = {
    "num_cpus",
    "num_gpus",
    "num_tpus",
    "num_returns",
    "resources",
    "max_retries",
    "retry_exceptions",
    "name",
    "scheduling_strategy",
    "placement_group",
    "placement_group_bundle_index",
    "runtime_env",
}


def _maybe_trace(runtime_env, task_name):
    """Inject span context when RAY_TPU_TRACE=1 (reference:
    tracing_helper.py _tracing_task_invocation)."""
    from .util import tracing

    return tracing.inject(runtime_env, task_name)


class RemoteFunction:
    def __init__(self, fn, **default_options):
        bad = set(default_options) - _VALID_OPTIONS
        if bad:
            raise ValueError(f"Invalid @remote options: {sorted(bad)}")
        self._fn = fn
        self._default_options = default_options
        self._blob: Optional[bytes] = None
        self._function_id: Optional[bytes] = None
        # Simple-options analysis, computed once: plain tasks (no
        # placement, no runtime_env, no retries-with-exceptions) take a
        # submit path that skips the per-call option plumbing.
        opts = default_options
        self._simple = not any(
            opts.get(k)
            for k in (
                "placement_group",
                "scheduling_strategy",
                "runtime_env",
                "name",
            )
        ) and (opts.get("placement_group_bundle_index") in (None, -1))
        self._resources = _submit.resources_from_options(opts)
        self._num_returns = opts.get("num_returns", 1) or 1
        # Reference default: tasks retry 3x on SYSTEM failure (worker
        # crash / node loss), never on application exceptions unless
        # retry_exceptions is set (ray_constants.DEFAULT_TASK_MAX_RETRIES).
        mr = opts.get("max_retries")
        self._max_retries = 3 if mr is None else mr
        self._retry_exceptions = bool(opts.get("retry_exceptions", False))
        functools.update_wrapper(self, fn)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function '{self._fn.__name__}' cannot be called directly; "
            f"use .remote()."
        )

    def options(self, **options) -> "RemoteFunction":
        merged = _submit.resolve_options(self._default_options, options)
        clone = RemoteFunction(self._fn, **merged)
        clone._blob = self._blob
        clone._function_id = self._function_id
        return clone

    def bind(self, *args, **kwargs):
        """Build a lazy DAG node instead of executing (reference:
        ray.dag — dag_node.py); run with ``.execute()`` or hand to
        ``workflow.run``."""
        from .dag import FunctionNode

        return FunctionNode(self, args, kwargs)

    def _ensure_pickled(self):
        if self._blob is None:
            self._blob = _submit.pickle_by_value(self._fn)
            self._function_id = _submit.function_id_for(self._blob)

    def remote(self, *args, **kwargs) -> Union[ObjectRef, List[ObjectRef]]:
        client = global_client()
        self._ensure_pickled()
        opts = self._default_options
        args_blob, deps, borrowed = _submit.prepare_args(args, kwargs)
        num_returns = self._num_returns
        if num_returns in ("streaming", "dynamic"):
            # Streaming generator: each yield seals as its own object,
            # reported incrementally; the caller iterates refs while the
            # task runs (reference: num_returns="streaming",
            # _raylet.pyx:1289). Routed via the GCS so stream_item
            # reports and scheduling share one ordered channel.
            return _submit.submit_streaming(
                client, self._fn.__name__, self._function_id,
                client.register_function_once(self._function_id, self._blob),
                args_blob, deps, _submit.resources_from_options(opts),
                borrowed=borrowed,
            )
        if self._simple:
            from .util import tracing

            if not tracing.enabled():
                spec = TaskSpec.__new__(TaskSpec)
                # Syscall-free id on the steady-state path; return
                # object ids derive from bytes [:12] which stay unique
                # (see ids.fast_unique_bytes).
                spec.task_id = TaskID(fast_unique_bytes())
                spec.name = self._fn.__name__
                spec.function_id = self._function_id
                spec.function_blob = client.register_function_once(
                    self._function_id, self._blob
                )
                spec.args_blob = args_blob
                spec.dependencies = deps
                spec.borrowed_refs = borrowed
                spec.num_returns = num_returns
                spec.resources = self._resources
                spec.actor_creation = False
                spec.actor_id = None
                spec.method_name = ""
                spec.max_restarts = 0
                spec.max_retries = self._max_retries
                spec.retry_exceptions = self._retry_exceptions
                spec.max_concurrency = 1
                spec.placement_group_id = None
                spec.placement_group_bundle_index = -1
                spec.scheduling_strategy = None
                spec.actor_name = None
                spec.lifetime = None
                spec.runtime_env = None
                spec.concurrency_groups = None
                spec.concurrency_group = None
                refs = client.submit_task_leased(spec)
                if refs is None:
                    refs = client.submit(spec)
                return refs[0] if num_returns == 1 else refs
        pg = opts.get("placement_group")
        pg_id: Optional[PlacementGroupID] = None
        bundle_index = opts.get("placement_group_bundle_index", -1)
        strategy = opts.get("scheduling_strategy")
        if strategy is not None and hasattr(strategy, "placement_group"):
            pg = strategy.placement_group
            bundle_index = strategy.placement_group_bundle_index
        if pg is not None:
            pg_id = pg.id if hasattr(pg, "id") else pg
        spec = TaskSpec(
            task_id=TaskID.from_random(),
            name=opts.get("name") or self._fn.__name__,
            function_id=self._function_id,
            function_blob=client.register_function_once(self._function_id, self._blob),
            args_blob=args_blob,
            dependencies=deps,
            borrowed_refs=borrowed,
            num_returns=num_returns,
            resources=_submit.resources_from_options(opts),
            max_retries=(
                3
                if opts.get("max_retries") is None
                else opts["max_retries"]
            ),
            retry_exceptions=bool(opts.get("retry_exceptions", False)),
            placement_group_id=pg_id,
            placement_group_bundle_index=(
                bundle_index if bundle_index is not None else -1
            ),
            scheduling_strategy=_submit.normalize_strategy(strategy),
            runtime_env=_submit.prepare_runtime_env(
                _maybe_trace(opts.get("runtime_env"),
                             opts.get("name") or self._fn.__name__),
                client,
            ),
        )
        # Leased direct transport for plain tasks (no deps/PG/TPU); falls
        # back to GCS-routed scheduling (reference: direct task submitter
        # vs GCS-scheduled tasks, direct_task_transport.cc:24).
        refs = client.submit_task_leased(spec)
        if refs is None:
            refs = client.submit(spec)
        return refs[0] if num_returns == 1 else refs
