"""In-program pipeline parallelism: GPipe-style microbatch rotation.

Reference parity: the reference drives pipeline stages from the host —
compiled-DAG actors shuttling activations through mutable channels
(python/ray/dag/compiled_dag_node.py) or third-party integrations; it
has no native in-graph PP training path (SURVEY.md §2.3 flags this as a
rebuild goal). On TPU the idiomatic design is the opposite of
host-driven: put the *whole* pipeline schedule inside one jitted SPMD
program over a `pipe` mesh axis and let collective permutes move
activations over ICI.

Design (the scaling-book recipe):
  - Each device along the `pipe` axis holds ONE stage's parameters
    (a pytree stacked on a leading axis of size S = n_stages).
  - The schedule runs T = M + S - 1 ticks (M = n_microbatches). At tick
    t, stage 0 ingests microbatch t while stage s processes the
    activation that entered at tick t - s; between ticks every stage
    hands its output to its right neighbor with one `lax.ppermute`
    (nearest-neighbor ICI hop — the cheapest collective on a torus).
  - Bubble fraction is (S-1)/(M+S-1), exactly the GPipe figure; the
    transform is differentiable (the transpose of ppermute is the
    reverse ppermute), so `jax.grad` of a pipelined loss yields the
    backward pipeline automatically — no hand-written 1F1B schedule,
    XLA overlaps the permutes with stage compute.

Constraints: every stage must map activations of one shape to the same
shape (true for stacked transformer blocks); the microbatched input is
visible to all pipe devices (stage 0 reads it, others ignore it — for
very long inputs shard it on `data`/`seq` axes orthogonal to `pipe`).
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu._compat import shard_map


def stack_stage_params(per_stage: Sequence[Any]):
    """Stack S per-stage parameter pytrees on a new leading axis so the
    stack shards one-stage-per-device over the `pipe` axis."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_stage)


def pipeline_spec(mesh: Mesh, axis: str = "pipe"):
    """(params_spec, replicated_spec) for placing stacked stage params
    and everything else."""
    return NamedSharding(mesh, P(axis)), NamedSharding(mesh, P())


def pipelined(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    *,
    mesh: Mesh,
    axis: str = "pipe",
    n_microbatches: int,
    remat: bool = False,
) -> Callable[[Any, jax.Array], jax.Array]:
    """Lift ``stage_fn(stage_params, x) -> y`` (one pipeline stage) into
    a full S-stage pipelined apply over the mesh's ``axis``.

    Returns ``apply(stacked_params, x)`` where ``stacked_params`` has
    leading axis S (see :func:`stack_stage_params`) and ``x`` is
    ``[M, microbatch, ...]`` (M = ``n_microbatches``). The result is the
    composition stage_{S-1}(...stage_0(x)) per microbatch, replicated
    across the pipe axis. Differentiable; wrap in ``jax.jit`` (or call
    under an outer pjit) for real use.
    """
    if axis not in mesh.axis_names:
        raise ValueError(f"mesh has no axis {axis!r}: {mesh.axis_names}")
    n_stages = mesh.shape[axis]
    M = n_microbatches
    if remat:
        stage_fn = jax.checkpoint(stage_fn)

    def _spmd(stacked_params, x):
        # Inside shard_map: params carry a leading axis of size 1 (this
        # device's stage); x is replicated along `axis`.
        my_params = jax.tree_util.tree_map(lambda p: p[0], stacked_params)
        stage_idx = lax.axis_index(axis)
        S = n_stages
        perm = [(i, (i + 1) % S) for i in range(S)]
        out0 = jnp.zeros((M,) + x.shape[1:], x.dtype)
        state0 = jnp.zeros(x.shape[1:], x.dtype)

        def tick(carry, t):
            state, outbuf = carry
            # Stage 0 ingests microbatch t (clamped: ticks >= M are
            # drain-only); downstream stages consume the rotated state.
            x_t = lax.dynamic_index_in_dim(
                x, jnp.clip(t, 0, M - 1), axis=0, keepdims=False
            )
            inp = jnp.where(stage_idx == 0, x_t, state)
            y = stage_fn(my_params, inp)
            # The last stage commits finished microbatch t-(S-1).
            out_t = t - (S - 1)
            valid = jnp.logical_and(
                stage_idx == S - 1,
                jnp.logical_and(out_t >= 0, out_t < M),
            )
            committed = lax.dynamic_update_index_in_dim(
                outbuf,
                jnp.where(valid, y, lax.dynamic_index_in_dim(
                    outbuf, jnp.clip(out_t, 0, M - 1), axis=0, keepdims=False
                )),
                jnp.clip(out_t, 0, M - 1),
                axis=0,
            )
            state = lax.ppermute(y, axis, perm)
            return (state, committed), None

        (_, outbuf), _ = lax.scan(
            tick, (state0, out0), jnp.arange(M + S - 1)
        )
        # Only the last stage holds real outputs; psum over the pipe
        # axis replicates them (everyone else contributes zeros).
        mask = (stage_idx == S - 1).astype(outbuf.dtype)
        return lax.psum(outbuf * mask, axis)

    # A single PartitionSpec acts as a pytree prefix: every param leaf
    # shards its stage axis over `axis`; x and the output replicate.
    apply = shard_map(
        _spmd,
        mesh=mesh,
        in_specs=(P(axis), P()),
        out_specs=P(),
        check_vma=False,
        axis_names={axis},
    )

    @functools.wraps(stage_fn)
    def wrapped(stacked_params, x):
        if x.shape[0] != M:
            raise ValueError(
                f"expected leading microbatch axis {M}, got {x.shape[0]}"
            )
        return apply(stacked_params, x)

    return wrapped


def sequential_reference(stage_fn, per_stage_params, x):
    """Unpipelined oracle: fold the stages over each microbatch. Used by
    tests to pin pipelined numerics."""
    def one(mb):
        for p in per_stage_params:
            mb = stage_fn(p, mb)
        return mb

    return jax.vmap(one)(x)
