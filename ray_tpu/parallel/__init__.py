from .mesh import (  # noqa: F401
    MeshSpec,
    LOGICAL_RULES,
    logical_sharding,
    shard_params,
    with_logical_constraint,
)
