"""Device meshes and sharding rules: the TPU parallelism substrate.

Where the reference scales out with NCCL process groups wired by Train
backends (reference: train/torch/config.py:35 init_process_group,
util/collective nccl groups), a TPU framework declares a
`jax.sharding.Mesh` with named axes and lets XLA compile collectives
over ICI into the program (GSPMD). Five axes cover the strategies in
SURVEY.md §2.3:

  data    -- pure data parallelism (gradient allreduce)
  fsdp    -- data parallelism with sharded params/optimizer (ZeRO-3:
             params all-gathered per layer, grads reduce-scattered)
  seq     -- sequence/context parallelism (ring attention over ICI)
  tensor  -- megatron-style tensor parallelism within a layer
  expert  -- expert parallelism for MoE layers

Logical axis names on arrays map to mesh axes through LOGICAL_RULES
(flax logical-partitioning convention), so models annotate *meaning*
("embed", "heads") and deployment picks the mesh.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_ORDER = ("data", "fsdp", "seq", "tensor", "expert")

# logical axis -> mesh axis (or tuple of mesh axes). First matching rule
# wins; None means replicate.
LOGICAL_RULES: List[Tuple[str, Any]] = [
    ("batch", ("data", "fsdp")),
    ("seq", "seq"),
    ("embed", "fsdp"),
    ("heads", "tensor"),
    ("kv_heads", "tensor"),
    ("qkv", None),
    ("mlp", "tensor"),
    ("vocab", "tensor"),
    ("expert", "expert"),
    ("norm", None),
    ("head_dim", None),
]


@dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape; ScalingConfig carries one of these
    (reference equivalent: ScalingConfig num_workers/use_gpu —
    air/config.py — reimagined as axis sizes over a TPU slice)."""

    data: int = 1
    fsdp: int = 1
    seq: int = 1
    tensor: int = 1
    expert: int = 1

    def axis_sizes(self) -> Dict[str, int]:
        return {
            "data": self.data,
            "fsdp": self.fsdp,
            "seq": self.seq,
            "tensor": self.tensor,
            "expert": self.expert,
        }

    @property
    def num_devices(self) -> int:
        return self.data * self.fsdp * self.seq * self.tensor * self.expert

    @classmethod
    def for_devices(cls, n: int, *, strategy: str = "fsdp") -> "MeshSpec":
        """Fill one axis with all devices (simple presets)."""
        if strategy not in AXIS_ORDER:
            raise ValueError(f"strategy must be one of {AXIS_ORDER}")
        return cls(**{strategy: n})

    def build(self, devices: Optional[Sequence[Any]] = None) -> Mesh:
        devices = list(devices if devices is not None else jax.devices())
        if len(devices) < self.num_devices:
            raise ValueError(
                f"MeshSpec needs {self.num_devices} devices, have {len(devices)}"
            )
        devices = devices[: self.num_devices]
        shape = tuple(self.axis_sizes()[a] for a in AXIS_ORDER)
        arr = np.array(devices, dtype=object).reshape(shape)
        return Mesh(arr, AXIS_ORDER)


def mesh_axes_for_logical(logical: str) -> Any:
    for name, axes in LOGICAL_RULES:
        if name == logical:
            return axes
    return None


def logical_to_spec(logical_axes: Sequence[Optional[str]]) -> P:
    """("batch", "seq", "embed") -> PartitionSpec(("data","fsdp"), "seq", "fsdp")."""
    out = []
    used: set = set()
    for ax in logical_axes:
        mesh_axes = mesh_axes_for_logical(ax) if ax is not None else None
        # A mesh axis may appear at most once in a PartitionSpec.
        if mesh_axes is not None:
            flat = mesh_axes if isinstance(mesh_axes, tuple) else (mesh_axes,)
            if any(a in used for a in flat):
                mesh_axes = None
            else:
                used.update(flat)
        out.append(mesh_axes)
    return P(*out)


def logical_sharding(mesh: Mesh, logical_axes: Sequence[Optional[str]]) -> NamedSharding:
    return NamedSharding(mesh, logical_to_spec(logical_axes))


def _ambient_mesh_axis_names():
    """Axis names of the ambient mesh: jax.set_mesh context first, then
    the legacy `with mesh:` resource env. None if neither is active."""
    try:
        mesh = jax.sharding.get_abstract_mesh()
        if mesh is not None and mesh.axis_names:
            return mesh.axis_names
    except Exception:
        pass
    try:
        from jax._src import mesh as mesh_lib

        phys = mesh_lib.thread_resources.env.physical_mesh
        if phys is not None and not phys.empty:
            return phys.axis_names
    except Exception:
        pass
    return None


def with_logical_constraint(x, logical_axes: Sequence[Optional[str]]):
    """In-jit sharding constraint by logical axis names. No-op when there
    is no ambient mesh (single-device runs, unit tests) so model code can
    annotate unconditionally. Honors both `jax.set_mesh` and the legacy
    `with mesh:` context."""
    axis_names = _ambient_mesh_axis_names()
    if axis_names is None:
        return x
    spec = logical_to_spec(logical_axes)
    # Drop axes the ambient mesh doesn't have.
    cleaned = []
    for entry in spec:
        if entry is None:
            cleaned.append(None)
        elif isinstance(entry, tuple):
            kept = tuple(a for a in entry if a in axis_names)
            cleaned.append(kept if kept else None)
        else:
            cleaned.append(entry if entry in axis_names else None)
    return jax.lax.with_sharding_constraint(x, P(*cleaned))


def spec_for_param(path: Tuple[str, ...], shape: Tuple[int, ...]) -> P:
    """Heuristic PartitionSpec for a parameter by name, used when a model
    doesn't carry explicit logical axes. Matmul weights shard (in=fsdp,
    out=tensor); embeddings shard (vocab=tensor, embed=fsdp); 1-D scales
    replicate."""
    if len(shape) <= 1:
        return P()
    name = "/".join(str(p) for p in path).lower()
    if "embed" in name and len(shape) == 2:
        return P("tensor", "fsdp")
    if len(shape) == 2:
        return P("fsdp", "tensor")
    if len(shape) == 3 and ("expert" in name or "w_gate" in name
                            or "w_up" in name or "w_down" in name):
        # Stacked MoE expert weights [E, in, out]: expert-parallel first
        # axis, then the usual (fsdp, tensor) matmul split.
        if "w_down" in name:
            return P("expert", "tensor", "fsdp")
        return P("expert", "fsdp", "tensor")
    if len(shape) == 3:  # e.g. (heads, head_dim, embed) attention proj
        return P("tensor", None, "fsdp")
    return P(*([None] * len(shape)))


def shard_params(params, mesh: Mesh, rules=None):
    """Place a parameter pytree on the mesh: explicit flax
    ``nn.with_partitioning`` metadata wins; otherwise spec_for_param."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]

    def place(path, leaf):
        spec = spec_for_param(
            tuple(getattr(p, "key", getattr(p, "idx", "")) for p in path),
            getattr(leaf, "shape", ()),
        )
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    leaves = [place(path, leaf) for path, leaf in flat]
    treedef = jax.tree_util.tree_structure(params)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def pad_to_multiple(n: int, k: int) -> int:
    return int(math.ceil(n / k) * k)
