"""Actor classes and handles.

Reference: python/ray/actor.py — ActorClass._remote :854 (creation) and
ActorMethod._remote :278 (method calls). Creation is centrally scheduled
through the control plane (the reference's GcsActorManager/-Scheduler);
method calls route to the actor's pinned worker in submission order.
"""
from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Union

from ._private import submit as _submit
from ._private.ids import ActorID, PlacementGroupID, TaskID
from ._private.task_spec import TaskSpec
from ._private.worker import global_client
from .object_ref import ObjectRef

def _maybe_trace(runtime_env, name):
    from .util import tracing

    return tracing.inject(runtime_env, name)


_VALID_ACTOR_OPTIONS = {
    "num_cpus",
    "num_gpus",
    "num_tpus",
    "resources",
    "name",
    "lifetime",
    "max_restarts",
    "max_task_retries",
    "max_concurrency",
    "concurrency_groups",
    "get_if_exists",
    "scheduling_strategy",
    "placement_group",
    "placement_group_bundle_index",
    "runtime_env",
}


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str,
                 num_returns: int = 1,
                 concurrency_group: Optional[str] = None):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns
        self._concurrency_group = concurrency_group

    def options(self, *, num_returns: Optional[int] = None,
                name: Optional[str] = None,
                concurrency_group: Optional[str] = None):
        return ActorMethod(
            self._handle, self._method_name,
            num_returns or self._num_returns,
            concurrency_group or self._concurrency_group,
        )

    def bind(self, *args, **kwargs):
        """Lazy actor-method call node for DAGs / compiled graphs."""
        from .dag import ClassMethodNode

        return ClassMethodNode(self, args, kwargs)

    def remote(self, *args, **kwargs) -> Union[ObjectRef, List[ObjectRef]]:
        client = global_client()
        args_blob, deps, borrowed = _submit.prepare_args(args, kwargs)
        if borrowed:
            # Actor-method deps never gate dispatch (the pinned worker
            # resolves args itself), so nested refs can ride the same
            # pin path as top-level ones: client-side pinning on the
            # direct route, head-side task_pins + pin→borrow conversion
            # on the GCS route.
            deps = deps + borrowed
        if self._num_returns in ("streaming", "dynamic"):
            # Streaming actor method: GCS-routed so the pinned worker's
            # stream_item reports and ordered dispatch share a channel.
            return _submit.submit_streaming(
                client, self._method_name, self._handle._class_function_id,
                None, args_blob, deps, {},
                actor_id=self._handle._actor_id,
                method_name=self._method_name,
            )
        # Steady state: compact frame straight down the established
        # direct connection — no TaskSpec, no GCS hop (reference: actor
        # calls go gRPC straight to the actor process). Frames carry a
        # per-call concurrency-group override; class-declared groups
        # resolve worker-side.
        refs = client.call_actor_fast(
            self._handle._actor_id.binary(),
            self._method_name,
            args_blob,
            self._num_returns,
            deps,
            self._concurrency_group,
        )
        if refs is None:
            spec = TaskSpec(
                task_id=TaskID.from_random(),
                name=f"{self._method_name}",
                function_id=self._handle._class_function_id,
                function_blob=None,
                args_blob=args_blob,
                dependencies=deps,
                num_returns=self._num_returns,
                resources={},
                actor_id=self._handle._actor_id,
                method_name=self._method_name,
                concurrency_group=self._concurrency_group,
            )
            # Route resolution / buffering path; None means route via
            # the GCS (restartable actors, actor pending, remote node).
            refs = client.submit_actor_direct(spec)
            if refs is None:
                refs = client.submit(spec)
        return refs[0] if self._num_returns == 1 else refs

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method '{self._method_name}' cannot be called directly; "
            f"use .remote()."
        )


class ActorHandle:
    def __init__(self, actor_id: ActorID, class_function_id: bytes = b"\x00" * 16):
        self._actor_id = actor_id
        self._class_function_id = class_function_id

    def __getattr__(self, name: str) -> ActorMethod:
        if name == "__ray_apply__":
            # Framework-internal: apply a shipped function to the actor
            # instance (compiled-graph loops) — see worker_main.
            return ActorMethod(self, "__ray_apply__")
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self, name)

    def __ray_terminate__(self):  # pragma: no cover - attribute shadow helper
        raise TypeError("use handle.__ray_terminate__.remote()")

    @property
    def __ray_terminate_method__(self) -> ActorMethod:
        return ActorMethod(self, "__ray_terminate__")

    def terminate(self) -> ObjectRef:
        """Graceful exit: queued behind pending method calls."""
        return ActorMethod(self, "__ray_terminate__").remote()

    def __repr__(self):
        return f"ActorHandle({self._actor_id.hex()})"

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._class_function_id))


class ActorClass:
    def __init__(self, cls: type, **default_options):
        bad = set(default_options) - _VALID_ACTOR_OPTIONS
        if bad:
            raise ValueError(f"Invalid actor options: {sorted(bad)}")
        self._cls = cls
        self._default_options = default_options
        self._blob: Optional[bytes] = None
        self._function_id: Optional[bytes] = None
        functools.update_wrapper(self, cls, updated=[])

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class '{self._cls.__name__}' cannot be instantiated directly; "
            f"use {self._cls.__name__}.remote()."
        )

    def options(self, **options) -> "ActorClass":
        merged = _submit.resolve_options(self._default_options, options)
        clone = ActorClass(self._cls, **merged)
        clone._blob = self._blob
        clone._function_id = self._function_id
        return clone

    def _ensure_pickled(self):
        if self._blob is None:
            self._blob = _submit.pickle_by_value(self._cls)
            self._function_id = _submit.function_id_for(self._blob)

    def remote(self, *args, **kwargs) -> ActorHandle:
        client = global_client()
        self._ensure_pickled()
        opts = self._default_options
        name = opts.get("name")
        actor_id = ActorID.from_random()
        if name:
            # Atomic name reservation in the GCS (get-or-create).
            reply = client.request(
                {
                    "type": "reserve_actor_name",
                    "name": name,
                    "actor_id": actor_id.binary(),
                }
            )
            if not reply.get("created"):
                if opts.get("get_if_exists"):
                    return ActorHandle(ActorID(reply["actor_id"]), self._function_id)
                raise ValueError(f"Actor name '{name}' is already taken")
        try:
            args_blob, deps, borrowed = _submit.prepare_args(args, kwargs)
        except BaseException:
            if name:
                client.send(
                    {
                        "type": "release_actor_name",
                        "name": name,
                        "actor_id": actor_id.binary(),
                    }
                )
            raise
        pg = opts.get("placement_group")
        bundle_index = opts.get("placement_group_bundle_index", -1)
        strategy = opts.get("scheduling_strategy")
        if strategy is not None and hasattr(strategy, "placement_group"):
            pg = strategy.placement_group
            bundle_index = strategy.placement_group_bundle_index
        pg_id: Optional[PlacementGroupID] = None
        if pg is not None:
            pg_id = pg.id if hasattr(pg, "id") else pg
        spec = TaskSpec(
            task_id=TaskID.from_random(),
            name=f"{self._cls.__name__}.__init__",
            function_id=self._function_id,
            function_blob=client.register_function_once(self._function_id, self._blob),
            args_blob=args_blob,
            dependencies=deps,
            borrowed_refs=borrowed,
            num_returns=1,
            resources=_submit.resources_from_options(opts, is_actor=True),
            actor_creation=True,
            actor_id=actor_id,
            max_restarts=opts.get("max_restarts", 0) or 0,
            max_concurrency=opts.get("max_concurrency", 1) or 1,
            concurrency_groups=opts.get("concurrency_groups"),
            actor_name=name,
            lifetime=opts.get("lifetime"),
            placement_group_id=pg_id,
            placement_group_bundle_index=(
                bundle_index if bundle_index is not None else -1
            ),
            scheduling_strategy=_submit.normalize_strategy(strategy),
            runtime_env=_submit.prepare_runtime_env(
                _maybe_trace(
                    opts.get("runtime_env"), f"{self._cls.__name__}.__init__"
                ),
                client,
            ),
        )
        client.submit(spec)
        return ActorHandle(actor_id, self._function_id)
