"""ObjectRef: a first-class future naming an immutable object.

Reference: the C++ ObjectID + Python ObjectRef (python/ray/includes/
object_ref.pxi). Refs are picklable; passing a ref inside a task arg or
return value keeps naming the same object (the reference calls this
borrowing — reference_count.h:61). Round-1 lifetime model: objects live
for the session (directory-driven free instead of distributed refcount).
"""
from __future__ import annotations

from ._private.ids import ObjectID


class ObjectRef:
    __slots__ = ("_id", "_owner")

    def __init__(self, object_id: ObjectID, owner: bytes = b""):
        self._id = object_id
        self._owner = owner

    def id(self) -> ObjectID:
        return self._id

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    def __reduce__(self):
        return (ObjectRef, (self._id, self._owner))

    def future(self):
        """Return a concurrent.futures.Future resolving to the value."""
        from . import get as _get
        import concurrent.futures
        import threading

        fut: concurrent.futures.Future = concurrent.futures.Future()

        def _resolve():
            try:
                fut.set_result(_get(self))
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        threading.Thread(target=_resolve, daemon=True).start()
        return fut

    def __await__(self):
        import asyncio

        return asyncio.wrap_future(self.future()).__await__()
