"""ObjectRef: a first-class future naming an immutable object.

Reference: the C++ ObjectID + Python ObjectRef (python/ray/includes/
object_ref.pxi). Refs are picklable; passing a ref inside a task arg or
return value keeps naming the same object (the reference calls this
borrowing — reference_count.h:61). Lifetime: every live instance counts
toward the process's local refcount (ref_tracker.py); when the last
instance across all clients dies, the GCS directory frees the object.
"""
from __future__ import annotations

import threading

from ._private import ref_tracker
from ._private.ids import ObjectID

# Active capture lists (serialization.dumps collects the refs nested in
# a value being stored, so the directory can pin them as children —
# the borrowing protocol's "refs inside objects" case).
_capture = threading.local()


class _CaptureRefs:
    """Context manager collecting ObjectRefs pickled within its scope."""

    def __enter__(self):
        self.seen = []
        stack = getattr(_capture, "stack", None)
        if stack is None:
            stack = _capture.stack = []
        stack.append(self.seen)
        return self

    def __exit__(self, *exc):
        _capture.stack.pop()
        return False


class ObjectRef:
    __slots__ = ("_id", "_owner", "__weakref__")

    def __init__(self, object_id: ObjectID, owner: bytes = b""):
        self._id = object_id
        self._owner = owner
        ref_tracker.track(object_id.binary())

    def __del__(self):
        try:
            ref_tracker.untrack(self._id.binary())
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass

    def id(self) -> ObjectID:
        return self._id

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    def __reduce__(self):
        stack = getattr(_capture, "stack", None)
        if stack:
            stack[-1].append(self._id.binary())
        return (ObjectRef, (self._id, self._owner))

    def future(self):
        """Return a concurrent.futures.Future resolving to the value."""
        from . import get as _get
        import concurrent.futures
        import threading

        fut: concurrent.futures.Future = concurrent.futures.Future()

        def _resolve():
            try:
                fut.set_result(_get(self))
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        threading.Thread(target=_resolve, daemon=True).start()
        return fut

    def __await__(self):
        import asyncio

        return asyncio.wrap_future(self.future()).__await__()
