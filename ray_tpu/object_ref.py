"""ObjectRef: a first-class future naming an immutable object.

Reference: the C++ ObjectID + Python ObjectRef (python/ray/includes/
object_ref.pxi). Refs are picklable; passing a ref inside a task arg or
return value keeps naming the same object (the reference calls this
borrowing — reference_count.h:61). Lifetime: every live instance counts
toward the process's local refcount (ref_tracker.py); when the last
instance across all clients dies, the GCS directory frees the object.
"""
from __future__ import annotations

import threading

from ._private import ref_tracker
from ._private.ids import ObjectID

# Active capture lists (serialization.dumps collects the refs nested in
# a value being stored, so the directory can pin them as children —
# the borrowing protocol's "refs inside objects" case).
_capture = threading.local()


class _CaptureRefs:
    """Context manager collecting ObjectRefs pickled within its scope."""

    def __enter__(self):
        self.seen = []
        stack = getattr(_capture, "stack", None)
        if stack is None:
            stack = _capture.stack = []
        stack.append(self.seen)
        return self

    def __exit__(self, *exc):
        _capture.stack.pop()
        return False


class ObjectRef:
    __slots__ = ("_id", "_owner", "__weakref__")

    def __init__(self, object_id: ObjectID, owner: bytes = b""):
        self._id = object_id
        self._owner = owner
        # The owner rides into the tracker: instances of objects this
        # process owns count locally with zero wire traffic; borrowed
        # refs report borrow edges to their owner (object_plane).
        ref_tracker.track(object_id.binary(), owner)

    def __del__(self):
        try:
            ref_tracker.untrack(self._id.binary())
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass

    def id(self) -> ObjectID:
        return self._id

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    def __reduce__(self):
        stack = getattr(_capture, "stack", None)
        if stack:
            stack[-1].append(self._id.binary())
        return (ObjectRef, (self._id, self._owner))

    def future(self):
        """Return a concurrent.futures.Future resolving to the value."""
        from . import get as _get
        import concurrent.futures
        import threading

        fut: concurrent.futures.Future = concurrent.futures.Future()

        def _resolve():
            try:
                fut.set_result(_get(self))
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        threading.Thread(target=_resolve, daemon=True).start()
        return fut

    def __await__(self):
        import asyncio

        return asyncio.wrap_future(self.future()).__await__()


_STREAM_END = object()


class ObjectRefGenerator:
    """Iterator of ObjectRefs produced by a ``num_returns="streaming"``
    task (reference: StreamingObjectRefGenerator, _raylet.pyx:1289).

    Each ``next()`` blocks until the executor has sealed the next yield
    as its own object (reported incrementally through the control
    plane), then returns its ref — the consumer observes outputs while
    the task is still running. A generator that raises mid-stream
    delivers the error on the next() after its last yield. Not
    picklable (consume where created); lineage reconstruction does not
    cover streamed outputs.
    """

    def __init__(self, task_id: bytes, client, owner: bytes):
        self._task_id = task_id
        self._client = client
        self._owner = owner
        self._index = 0

    def __iter__(self):
        return self

    def __next__(self) -> "ObjectRef":
        nxt = self._next_or_end()
        if nxt is _STREAM_END:
            raise StopIteration
        return nxt

    def _next_or_end(self):
        reply = self._client.request(
            {
                "type": "stream_next",
                "task_id": self._task_id,
                "index": self._index,
            }
        )
        if reply.get("available"):
            oid = ObjectID(ObjectID.bytes_for_return(self._task_id, self._index))
            self._index += 1
            # Ownerless on purpose: stream items are sealed head-side by
            # the executor (owner None in the directory) and lineage
            # never covers streamed outputs — head-fallback holder
            # semantics free them on drop. Owner classification would
            # mean an owned-but-never-advertised ref whose drop sends
            # nothing, leaking every consumed item.
            ref = ObjectRef(oid, b"")
            # Advertised from birth: stream_next just confirmed the
            # head entry exists, so the eventual drop must send its
            # remove even when the item is consumed and dropped within
            # one flush window (otherwise fast drain loops leak every
            # item — the entry has no other holder).
            tracker = getattr(self._client, "_tracker", None)
            if tracker is not None:
                tracker.mark_advertised(oid.binary())
            return ref
        err = reply.get("error")
        if err is not None:
            from ._private import serialization
            from .exceptions import RayTaskError

            e = serialization.unpack(err)
            if isinstance(e, RayTaskError):
                raise e.as_instanceof_cause()
            raise e
        return _STREAM_END

    def __aiter__(self):
        return self

    async def __anext__(self) -> "ObjectRef":
        import asyncio

        loop = asyncio.get_running_loop()
        nxt = await loop.run_in_executor(None, self._next_or_end)
        if nxt is _STREAM_END:
            raise StopAsyncIteration
        return nxt

    def completed(self) -> int:
        """Items yielded so far (refs this generator has handed out)."""
        return self._index

    def __repr__(self):
        return f"ObjectRefGenerator(task={self._task_id.hex()}, next={self._index})"
