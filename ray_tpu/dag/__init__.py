"""ray_tpu.dag: lazy DAGs of tasks and actor-method calls.

Reference: python/ray/dag/ (dag_node.py, input_node.py,
class_node.py, compiled_dag_node.py). ``fn.bind(...)`` builds the DAG
lazily; ``dag.execute(input)`` walks it with ordinary task submission;
``dag.experimental_compile()`` (actor-method DAGs) pre-allocates
channels and loops the actors on them, bypassing per-call RPC.
"""
from __future__ import annotations

from .dag_node import (  # noqa: F401
    ClassMethodNode,
    DAGNode,
    FunctionNode,
    InputNode,
)
from .compiled_dag import CompiledDAG  # noqa: F401

__all__ = [
    "ClassMethodNode",
    "CompiledDAG",
    "DAGNode",
    "FunctionNode",
    "InputNode",
]
