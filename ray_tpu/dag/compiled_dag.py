"""CompiledDAG: loop actors on pre-allocated channels.

Reference: python/ray/dag/compiled_dag_node.py — compiling an
actor-method DAG replaces per-call task RPC with single-slot
shared-memory channels (experimental_mutable_object_manager.cc) and a
resident loop in each actor: ~10x lower per-call overhead. Execution
becomes: write input channel → each actor reads its input channels,
runs its method, writes its output channel → read output channel.
"""
from __future__ import annotations

import threading
import time
import uuid
from typing import Any, Dict, List, Optional

import cloudpickle

from .._private.channel import Channel, ChannelClosed
from .dag_node import ClassMethodNode, DAGNode, InputNode


def _actor_loop(instance, method_name: str, in_specs, out_channel_name: str,
                const_args, const_kwargs):
    """Runs inside the actor (via __ray_apply__): read → call → write
    until the input channels close."""
    in_channels = [
        (pos, Channel(name)) for pos, name in in_specs
    ]
    out = Channel(out_channel_name)
    method = getattr(instance, method_name)
    try:
        while True:
            # Every channel carries ("ok", value) | ("err", exc) so an
            # upstream failure forwards through the pipeline to the
            # driver instead of poisoning a method call.
            args = list(const_args)
            upstream_err = None
            try:
                for pos, ch in in_channels:
                    status, payload = ch.read()
                    if status == "err":
                        upstream_err = payload
                    else:
                        args[pos] = payload
            except ChannelClosed:
                break
            if upstream_err is not None:
                out.write(("err", upstream_err))
                continue
            try:
                result = method(*args, **const_kwargs)
                out.write(("ok", result))
            except Exception as e:  # noqa: BLE001 - surface to caller
                out.write(("err", e))
    finally:
        out.close_writer()
        for _, ch in in_channels:
            ch.destroy()
        out.destroy()
    return "loop_done"


def _reject_nested_dag_nodes(value, where: str) -> None:
    """Compiled wiring only supports DAGNodes as top-level positional
    args; anything else would silently ship the node object as a
    constant. Fail loudly instead."""
    if isinstance(value, DAGNode):
        raise ValueError(
            f"CompiledDAG: DAGNode passed as {where}; compiled graphs "
            "support DAG inputs as top-level positional arguments only"
        )
    if isinstance(value, (list, tuple)):
        for v in value:
            _reject_nested_dag_nodes(v, where)
    elif isinstance(value, dict):
        for v in value.values():
            _reject_nested_dag_nodes(v, where)


class _CompiledStage:
    def __init__(self, node: ClassMethodNode):
        self.node = node
        self.in_specs: List = []  # (arg position, channel)
        self.out_channel: Optional[Channel] = None


class CompiledDAG:
    """Compile once, ``execute(input)`` many times. Supports linear and
    branching actor-method DAGs with a single InputNode and single
    output node."""

    def __init__(self, root: DAGNode, submit_timeout: float = 30.0):
        self._root = root
        self._timeout = submit_timeout
        self._stages: Dict[int, _CompiledStage] = {}
        self._input_channels: List[Channel] = []
        self._all_channels: List[Channel] = []  # driver owns/unlinks all
        self._output_channel: Optional[Channel] = None
        self._loop_refs = []
        self._destroyed = False
        self._inflight = 0
        # Guards _inflight for the feeder-thread/collector-thread
        # pipelining pattern (submit blocks on the bounded channels, so
        # keeping the pipe full needs a second thread).
        self._inflight_cv = threading.Condition()
        self._compile()

    # ------------------------------------------------------------ compile
    def _compile(self) -> None:
        order = self._root.topological_order()
        input_nodes = [n for n in order if isinstance(n, InputNode)]
        if len(input_nodes) > 1:
            raise ValueError("CompiledDAG supports exactly one InputNode")
        for node in order:
            if isinstance(node, InputNode):
                continue
            if not isinstance(node, ClassMethodNode):
                raise ValueError(
                    "CompiledDAG supports actor-method nodes only "
                    f"(got {type(node).__name__}); use .execute() for "
                    "task DAGs"
                )
            self._stages[id(node)] = _CompiledStage(node)

        # Wire channels: one per edge (fan-out gets one channel per
        # consumer since channels are SPSC).
        for node in order:
            if isinstance(node, InputNode):
                continue
            stage = self._stages[id(node)]
            const_args = []
            for pos, arg in enumerate(node._bound_args):
                if isinstance(arg, InputNode):
                    ch = Channel()
                    self._input_channels.append(ch)
                    self._all_channels.append(ch)
                    stage.in_specs.append((pos, ch))
                    const_args.append(None)
                elif isinstance(arg, DAGNode):
                    up = self._stages[id(arg)]
                    ch = Channel()
                    self._all_channels.append(ch)
                    if up.out_channel is not None:
                        raise ValueError(
                            "fan-out from one node to multiple consumers "
                            "is not yet supported in compiled mode"
                        )
                    up.out_channel = ch
                    stage.in_specs.append((pos, ch))
                    const_args.append(None)
                else:
                    _reject_nested_dag_nodes(arg, "positional arg")
                    const_args.append(arg)
            stage.const_args = const_args
            for k, v in node._bound_kwargs.items():
                _reject_nested_dag_nodes(v, f"kwarg {k!r}")
            stage.const_kwargs = dict(node._bound_kwargs)

        out_stage = self._stages[id(self._root)]
        self._output_channel = Channel()
        self._all_channels.append(self._output_channel)
        out_stage.out_channel = self._output_channel

        # Launch resident loops.
        for stage in self._stages.values():
            handle = stage.node.actor_handle
            loop_blob = cloudpickle.dumps(_actor_loop)
            ref = handle.__ray_apply__.remote(
                loop_blob,
                stage.node.method_name,
                [(pos, ch.name) for pos, ch in stage.in_specs],
                stage.out_channel.name,
                tuple(stage.const_args),
                stage.const_kwargs,
            )
            self._loop_refs.append(ref)

    # ------------------------------------------------------------ execute
    def _check_live(self) -> None:
        if self._destroyed:
            raise RuntimeError("CompiledDAG already torn down")
        if getattr(self, "_poisoned", False):
            raise RuntimeError(
                "CompiledDAG is poisoned: a previous operation timed out "
                "with a result still in flight (a later read would return "
                "the stale result). teardown() and re-compile."
            )

    def execute(self, *input_args) -> Any:
        self.submit(*input_args)
        return self.collect()

    def submit(self, *input_args) -> None:
        """Enqueue one input without waiting for its result — the
        pipelining half of execute() (reference: compiled-DAG
        execute() returns a future-like ref; here submit/collect split
        makes the microbatch pipeline explicit). Channels are
        single-slot, so total in-flight is bounded by the DAG's edge
        count: a submit into a full pipeline BLOCKS until a stage
        drains — natural backpressure. Results come out of collect()
        in submit order."""
        self._check_live()
        value = input_args[0] if len(input_args) == 1 else input_args
        try:
            for ch in self._input_channels:
                ch.write(("ok", value), timeout=self._timeout)
        except TimeoutError:
            self._poisoned = True
            raise
        with self._inflight_cv:
            self._inflight += 1
            self._inflight_cv.notify()

    def collect(self) -> Any:
        """Read the next result in submit (FIFO) order. With a feeder
        thread submitting concurrently, waits for the next submit to
        land rather than failing on the race."""
        self._check_live()
        with self._inflight_cv:
            # Grace window covers the feeder-thread race (submit is
            # microseconds from landing); a genuine collect-with-no-
            # submit still errors instead of parking self._timeout.
            if not self._inflight_cv.wait_for(
                lambda: self._inflight > 0, timeout=1.0
            ):
                raise RuntimeError("collect() without a matching submit()")
            self._inflight -= 1
        try:
            status, result = self._output_channel.read(timeout=self._timeout)
        except TimeoutError:
            self._poisoned = True
            raise
        if status == "err":
            raise result
        return result

    # ------------------------------------------------------------ teardown
    def teardown(self) -> None:
        if self._destroyed:
            return
        self._destroyed = True
        for ch in self._input_channels:
            ch.close_writer()
        import ray_tpu

        for ref in self._loop_refs:
            try:
                ray_tpu.get(ref, timeout=5.0)
            except Exception:  # noqa: BLE001
                pass
        for ch in self._all_channels:
            ch.destroy()

    def __del__(self):
        try:
            self.teardown()
        except Exception:  # noqa: BLE001
            pass
