"""DAG nodes (reference: python/ray/dag/dag_node.py).

A node captures (what to call, bound args) without executing. Args may
contain other DAGNodes — those become edges. ``execute`` memoizes per
node so diamond dependencies run once.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional


class DAGNode:
    def __init__(self, args: tuple, kwargs: dict):
        self._bound_args = args
        self._bound_kwargs = kwargs

    # ------------------------------------------------------------ traverse
    def _upstream(self) -> List["DAGNode"]:
        out = []

        def scan(v):
            if isinstance(v, DAGNode):
                out.append(v)
            elif isinstance(v, (list, tuple)):
                for x in v:
                    scan(x)
            elif isinstance(v, dict):
                for x in v.values():
                    scan(x)

        for a in self._bound_args:
            scan(a)
        for v in self._bound_kwargs.values():
            scan(v)
        return out

    def topological_order(self) -> List["DAGNode"]:
        seen: Dict[int, DAGNode] = {}
        order: List[DAGNode] = []

        def visit(node: DAGNode):
            if id(node) in seen:
                return
            seen[id(node)] = node
            for up in node._upstream():
                visit(up)
            order.append(node)

        visit(self)
        return order

    # ------------------------------------------------------------- execute
    def execute(self, *input_args, **input_kwargs):
        """Eagerly execute the DAG; returns the root's ObjectRef (or a
        plain value for InputNode roots)."""
        cache: Dict[int, Any] = {}
        for node in self.topological_order():
            cache[id(node)] = node._execute_node(cache, input_args, input_kwargs)
        return cache[id(self)]

    def _resolve_bound(self, cache: Dict[int, Any]):
        def sub(v):
            if isinstance(v, DAGNode):
                return cache[id(v)]
            if isinstance(v, list):
                return [sub(x) for x in v]
            if isinstance(v, tuple):
                return tuple(sub(x) for x in v)
            if isinstance(v, dict):
                return {k: sub(x) for k, x in v.items()}
            return v

        args = tuple(sub(a) for a in self._bound_args)
        kwargs = {k: sub(v) for k, v in self._bound_kwargs.items()}
        return args, kwargs

    def _execute_node(self, cache, input_args, input_kwargs):
        raise NotImplementedError

    def experimental_compile(self, **kwargs) -> "Any":
        from .compiled_dag import CompiledDAG

        return CompiledDAG(self, **kwargs)


class InputNode(DAGNode):
    """Placeholder for the value passed to ``execute``; supports
    context-manager syntax like the reference:

        with InputNode() as inp:
            dag = f.bind(inp)
    """

    def __init__(self):
        super().__init__((), {})

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def _execute_node(self, cache, input_args, input_kwargs):
        if len(input_args) == 1 and not input_kwargs:
            return input_args[0]
        if not input_args and not input_kwargs:
            return None
        return (input_args, input_kwargs)


class FunctionNode(DAGNode):
    """A remote-function invocation node (fn.bind(...))."""

    def __init__(self, remote_function, args: tuple, kwargs: dict,
                 options: Optional[dict] = None):
        super().__init__(args, kwargs)
        self._fn = remote_function
        self._options = options or {}

    def _execute_node(self, cache, input_args, input_kwargs):
        args, kwargs = self._resolve_bound(cache)
        fn = self._fn.options(**self._options) if self._options else self._fn
        return fn.remote(*args, **kwargs)

    @property
    def fn_name(self) -> str:
        return getattr(self._fn, "_name", None) or getattr(
            getattr(self._fn, "_fn", None), "__name__", "task"
        )


class ClassMethodNode(DAGNode):
    """An actor-method invocation node (actor.method.bind(...))."""

    def __init__(self, actor_method, args: tuple, kwargs: dict):
        super().__init__(args, kwargs)
        self._method = actor_method

    def _execute_node(self, cache, input_args, input_kwargs):
        args, kwargs = self._resolve_bound(cache)
        return self._method.remote(*args, **kwargs)

    @property
    def actor_handle(self):
        return self._method._handle

    @property
    def method_name(self) -> str:
        return self._method._method_name
